"""Device mesh construction with the five canonical parallel axes.

TPU-native scaling model (SURVEY.md §5.8): pick a mesh, annotate shardings,
let XLA insert collectives over ICI. Axes: dp (data), pp (pipeline stages),
tp (tensor/heads), sp (sequence/context), ep (experts). Any axis may be
size 1 — the sharding code paths stay identical.

Reduced-precision collectives (ISSUE 14, the EQuARX recipe — arxiv
2506.17615): :class:`ErrorFeedback` + :func:`reduced_precision_sum` /
:func:`two_level_allreduce` quantize each contribution AT THE REDUCTION
BOUNDARY (blockwise bf16 or int8-with-per-block-scale, sharing the wire
codecs in comm/wire.py so lane and wire round identically) and carry
the residual of each quantized send into the next contribution of the
same logical buffer — iterative workloads don't drift: the quantization
error is fed back, not discarded. The wave collective lane
(dsl/ptg/wave_dist.py, ``wave_reduce_dtype``) rides these helpers.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

AXES = ("dp", "pp", "tp", "sp", "ep")

#: declared lock discipline (analysis/lock_check.py): the error-feedback
#: accumulator is per-instance mutable state shared between whichever
#: threads drive the reduction (SPMD rank threads deposit concurrently
#: into one lane) — residuals live under the instance lock
_GUARDED_BY = {
    "ErrorFeedback._resid": "_lock",
}


def _factor(n: int, order: Sequence[str]) -> Dict[str, int]:
    """Greedy power-of-small-primes factoring of n over the axes in
    ``order`` (round-robin halving keeps the mesh balanced)."""
    sizes = {a: 1 for a in AXES}
    remaining = n
    # round-robin: repeatedly give the next axis the smallest prime factor
    i = 0
    while remaining > 1:
        p = _smallest_prime(remaining)
        sizes[order[i % len(order)]] *= p
        remaining //= p
        i += 1
    return sizes


def _smallest_prime(n: int) -> int:
    for p in (2, 3, 5, 7, 11, 13):
        if n % p == 0:
            return p
    return n


def make_mesh(n_devices: Optional[int] = None,
              sizes: Optional[Dict[str, int]] = None,
              devices: Optional[List] = None,
              order: Sequence[str] = ("dp", "tp", "sp", "pp", "ep")):
    """Build a 5-axis jax Mesh over ``n_devices`` (or explicit devices).

    With explicit ``sizes`` missing axes default to 1; otherwise n_devices
    is factored over ``order``.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devs = jax.devices()
        if n_devices is not None and len(devs) < n_devices:
            # a tunneled accelerator plugin may shadow the virtual CPU
            # mesh (xla_force_host_platform_device_count); fall back to it
            try:
                cpu = jax.devices("cpu")
                if len(cpu) >= n_devices:
                    devs = cpu
            except RuntimeError:
                pass
        if n_devices is not None:
            assert len(devs) >= n_devices, \
                f"need {n_devices} devices, have {len(devs)}"
            devs = devs[:n_devices]
    else:
        devs = list(devices)
    n = len(devs)
    if sizes is None:
        sizes = _factor(n, order)
    else:
        sizes = {**{a: 1 for a in AXES}, **sizes}
    total = int(np.prod([sizes[a] for a in AXES]))
    assert total == n, f"mesh sizes {sizes} != {n} devices"
    arr = np.array(devs).reshape([sizes[a] for a in AXES])
    return Mesh(arr, AXES)


def spec(*axes) -> "object":
    """PartitionSpec shorthand."""
    from jax.sharding import PartitionSpec as P
    return P(*axes)


# -- reduced-precision collectives with error feedback (ISSUE 14) -------
class ErrorFeedback:
    """Per-boundary error-feedback accumulator (EQuARX): for each
    logical buffer (caller-chosen ``key``) the residual of the last
    quantized send is retained and folded into the NEXT contribution
    before it quantizes, so repeated reductions of the same buffer
    converge to the full-precision result instead of accumulating
    bias. A key whose contribution shape changes starts fresh (it is a
    different buffer). Thread-safe: SPMD rank threads share one lane."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._resid: Dict[Any, np.ndarray] = {}

    def compensate(self, key: Any, arr: np.ndarray, codec: str,
                   qdq) -> np.ndarray:
        """Quantize ``arr`` through ``qdq(x, codec)`` with feedback:
        returns the quantized-dequantized values that should travel,
        retaining (folded contribution - sent values) for next time."""
        arr = np.asarray(arr)
        with self._lock:
            prev = self._resid.get(key)
            folded = (arr + prev if prev is not None
                      and prev.shape == arr.shape
                      and prev.dtype == arr.dtype else arr)
            out = qdq(folded, codec)
            self._resid[key] = folded - out
        return out

    def reset(self, key: Any = None) -> None:
        with self._lock:
            if key is None:
                self._resid.clear()
            else:
                self._resid.pop(key, None)

    def keys(self) -> List[Any]:
        with self._lock:
            return list(self._resid)


def _quant_codec_of(reduce_dtype: Optional[str]) -> Optional[str]:
    """Map a ``wave_reduce_dtype`` knob value to a registered quantized
    wire codec name (None = full precision)."""
    from ..comm import wire
    return wire.normalize_quant_codec(reduce_dtype or "")


# -- jit-native quantize hop (ISSUE 17) ---------------------------------
def qdq_jax(x: Any, codec: str) -> Any:
    """Traceable quantize-dequantize: jnp/lax ops only, and BIT-FOR-BIT
    the values :func:`wire.qdq_array` delivers (asserted by the parity
    test) — same RNE bf16 arithmetic on the raw uint32 bits, same
    blockwise absmax/127 f32 scales.  Usable inside a jit/shard_map
    body, so the reduction-boundary quantize lowers into the compiled
    collective instead of bouncing through host numpy."""
    import jax.numpy as jnp
    from jax import lax
    from ..comm.wire import QUANT_BLOCK
    if codec == "qbf16":
        dt = jnp.asarray(x).dtype
        u = lax.bitcast_convert_type(jnp.asarray(x, jnp.float32),
                                     jnp.uint32)
        # RNE: add 0x7FFF + the LSB of the kept half, then truncate —
        # the exact _enc_bf16 arithmetic, uint32 wraparound included
        q = ((u + jnp.uint32(0x7FFF)
              + ((u >> jnp.uint32(16)) & jnp.uint32(1)))
             >> jnp.uint32(16)).astype(jnp.uint16)
        f32 = lax.bitcast_convert_type(
            q.astype(jnp.uint32) << jnp.uint32(16), jnp.float32)
        return f32.astype(dt)
    if codec == "qint8":
        xa = jnp.asarray(x)
        n = xa.size
        nblocks = max(1, (n + QUANT_BLOCK - 1) // QUANT_BLOCK)
        xp = jnp.zeros(nblocks * QUANT_BLOCK, jnp.float32)
        xp = xp.at[:n].set(jnp.ravel(jnp.asarray(xa, jnp.float32)))
        xb = xp.reshape(nblocks, QUANT_BLOCK)
        # the divisor hides behind an optimization barrier: XLA:CPU
        # lowers division by a CONSTANT to reciprocal-multiply (1 ulp
        # off IEEE), which would break bit parity with the numpy codec
        # — an opaque runtime divisor keeps the correctly-rounded div
        c127 = lax.optimization_barrier(jnp.float32(127.0))
        scales = (jnp.abs(xb).max(axis=1) / c127).astype(jnp.float32)
        inv = jnp.where(scales > 0, 1.0 / scales, 0.0).astype(jnp.float32)
        q = jnp.clip(jnp.rint(xb * inv[:, None]),
                     -127, 127).astype(jnp.int8)
        deq = (q.astype(jnp.float32) * scales[:, None]).reshape(-1)[:n]
        return deq.reshape(xa.shape).astype(xa.dtype)
    raise ValueError(f"unknown quantized codec {codec!r}")


_QDQ_JIT: Dict[str, Any] = {}


def _qdq_native(arr: np.ndarray, codec: str) -> np.ndarray:
    """Numpy-in/numpy-out wrapper over the jit-compiled ``qdq_jax``
    (one compiled callable per codec, cached) — the drop-in boundary
    hop for the collective helpers below."""
    fn = _QDQ_JIT.get(codec)
    if fn is None:
        import jax
        fn = jax.jit(lambda v, c=codec: qdq_jax(v, c))
        _QDQ_JIT[codec] = fn
    a = np.ascontiguousarray(arr)
    # both codecs narrow through f32 before encoding (exactly what the
    # wire does) — feed f32 so a disabled-x64 jax cannot silently
    # truncate, and widen back to the caller's dtype on the way out
    out = np.asarray(fn(a.astype(np.float32, copy=False)))
    return out.astype(a.dtype, copy=False).reshape(a.shape)


def reduced_precision_sum(contribs: Sequence[np.ndarray],
                          reduce_dtype: Optional[str] = None,
                          feedback: Optional[ErrorFeedback] = None,
                          keys: Optional[Sequence[Any]] = None,
                          native: bool = True) -> np.ndarray:
    """Sum of per-participant contributions with quantize-at-the-
    boundary: each contribution is quantized (bf16 / int8 blockwise,
    exactly the wire codecs) before it enters the reduction —
    modelling what a reduced-precision all-reduce would move — and the
    accumulation itself stays full precision. ``feedback``/``keys``
    enable per-contributor error feedback (``keys[i]`` names
    contributor i's logical buffer). ``reduce_dtype`` None/"" keeps the
    exact full-precision sum (bit-for-bit the naive sum).  ``native``
    (the default) routes the boundary quantize through the jit-compiled
    :func:`qdq_jax` hop — bit-identical values (the parity contract),
    XLA-lowered arithmetic; ``native=False`` falls back to the eager
    host-numpy wire codec (kept for parity testing only)."""
    from ..comm import wire
    codec = _quant_codec_of(reduce_dtype)
    if codec is None:
        out = np.zeros_like(np.asarray(contribs[0]))
        for c in contribs:
            out = out + np.asarray(c)
        return out
    qdq = _qdq_native if native else wire.qdq_array
    out = None
    for i, c in enumerate(contribs):
        c = np.asarray(c)
        if feedback is not None and keys is not None:
            q = feedback.compensate(keys[i], c, codec, qdq)
        else:
            q = qdq(c, codec)
        out = q if out is None else out + q
    return out


def two_level_allreduce(shards: Sequence[np.ndarray],
                        group_size: int,
                        reduce_dtype: Optional[str] = None,
                        feedback: Optional[ErrorFeedback] = None,
                        key: Any = None,
                        native: bool = True) -> np.ndarray:
    """Hierarchical all-reduce: contributions reduce FULL-precision
    inside each ``group_size``-wide group (level 1 — the intra-mesh
    XLA psum over ICI, where bandwidth is plentiful), each group's
    partial sum quantizes at the group boundary (level 2 — the
    inter-rank hop over the wire, where it is not), and the quantized
    partials sum to the replicated result. With ``feedback`` set, each
    group's boundary residual is carried into its next partial under
    ``(key, group index)`` — the EQuARX error-feedback recipe. With
    ``reduce_dtype`` None/"" this is exactly the flat sum.  ``native``
    (the default) lowers the boundary quantize through the jit-compiled
    :func:`qdq_jax` hop (bit-identical values, XLA arithmetic);
    ``native=False`` is the eager host-numpy reference path."""
    n = len(shards)
    groups = [list(range(g, min(g + group_size, n)))
              for g in range(0, n, group_size)]
    partials = []
    for gi, members in enumerate(groups):
        part = np.asarray(shards[members[0]]).copy()
        for m in members[1:]:
            part += np.asarray(shards[m])
        partials.append(part)
    keys = [(key, gi) for gi in range(len(groups))] \
        if feedback is not None else None
    return reduced_precision_sum(partials, reduce_dtype,
                                 feedback=feedback, keys=keys,
                                 native=native)


def sync_axes(leaf_spec, mesh_axes: Sequence[str] = AXES) -> Tuple[str, ...]:
    """Mesh axes a parameter is REPLICATED over (its gradients must be
    psum'd across exactly these after manual-collective backprop)."""
    used = set()
    for entry in tuple(leaf_spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return tuple(a for a in mesh_axes if a not in used)


def _vma_of(x):
    import jax
    try:
        return set(jax.typeof(x).vma)
    except (AttributeError, TypeError):
        return None


def _pcast_varying(x, axes):
    from jax import lax
    try:
        return lax.pcast(x, axes, to="varying")
    except (AttributeError, TypeError):  # older jax spelling
        return lax.pvary(x, axes)


def match_vma(x, ref):
    """Promote ``x``'s varying-manual-axes (VMA) to cover ``ref``'s.

    Under check_vma=True, lax.scan requires carry input/output types to
    match exactly — fresh-zeros initial carries are 'unvarying' while the
    loop body makes them varying. Promote initials with this before scan.
    """
    cur, want_src = _vma_of(x), _vma_of(ref)
    if cur is None or want_src is None:
        return x
    want = tuple(sorted(want_src - cur))
    return _pcast_varying(x, want) if want else x


def axis_size(axis_name):
    """``lax.axis_size`` where it exists; pre-0.5 jax spells it as the
    literal-psum idiom (still a trace-time constant)."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def vary_on(x, axes, like=None):
    """Promote ``x`` to be varying on ``axes`` (plus ``like``'s VMA)."""
    cur = _vma_of(x)
    if cur is None:
        return x
    target = set(axes)
    if like is not None:
        target |= _vma_of(like) or set()
    want = tuple(sorted(target - cur))
    return _pcast_varying(x, want) if want else x


def shard_map_fwd(f, mesh, in_specs, out_specs):
    """Forward-only shard_map for DISPATCH (no autodiff through it):
    prefers the VMA-tracking ``jax.shard_map``, falls back to the
    ``jax.experimental`` spelling on older builds.

    The fallback is correct here precisely because nothing
    differentiates through a device dispatch — the two spellings only
    diverge in how psum transposes under grad (see
    :func:`shard_map_compat`, which therefore never falls back).
    Raises when neither spelling exists; callers treat that as
    "no mesh" and stay on the single-chip path."""
    import jax
    if hasattr(jax, "shard_map"):
        return shard_map_compat(f, mesh, in_specs, out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def xrank_mesh(devices):
    """One-axis ("xr") mesh over per-rank lane devices: the global
    mesh a cross-rank SPMD stage (stagec/xrank.py, ISSUE 20) compiles
    its shard_map program over.  Position p of the axis IS the p-th
    participating rank, so an ``all_gather`` over "xr" moves boundary
    tiles from producer-rank lanes to every participant in-program —
    the collective that replaces the serialized wire activation."""
    import numpy as _np
    from jax.sharding import Mesh
    return Mesh(_np.array(list(devices)), ("xr",))


def has_shard_map() -> bool:
    """True when SOME shard_map spelling exists (the gate for
    forward-only mesh dispatch; gradient-correct code must instead
    check ``hasattr(jax, "shard_map")`` — see shard_map_compat)."""
    import jax
    if hasattr(jax, "shard_map"):
        return True
    try:
        from jax.experimental.shard_map import shard_map  # noqa: F401
        return True
    except ImportError:
        return False


def shard_map_compat(f, mesh, in_specs, out_specs):
    """jax.shard_map with VMA (varying-manual-axes) tracking ON.

    check_vma=True is load-bearing for gradient correctness, not just
    checking: with it, psum transposes via the replication-aware rule and
    jax.grad of a REPLICATED leaf comes out already psum'd over exactly
    the axes its contributions were partial on — including the subtle
    cases (axes the forward never touches produce identity, mixed
    redundant+partial paths split correctly). With check_vma=False, psum
    transposes to psum and no per-leaf psum/pmean recipe is exact.
    """
    import jax
    try:
        sm = jax.shard_map
    except AttributeError:  # pre-0.5 jax: not yet promoted out
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_vma=True)
    except TypeError:  # older jax spelling
        return sm(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_rep=True)
