"""ServeClient: remote front-end for a SessionServer on another rank.

Requests ride ``TAG_SERVE`` active messages (versioned envelopes from
:mod:`parsec_tpu.comm.wire`); replies arrive on ``TAG_SERVE_REPLY`` and
are correlated by a per-client request id.  Over TCP both ends must
have negotiated the HELLO ``"sv"`` capability (``serve`` knob set on
both) — the client refuses to talk to a peer that did not, mirroring
the server-side gate, so a mixed-version fleet degrades to an explicit
error instead of silence.

The calling thread blocks on a condition variable until its reply is
delivered — which happens on whichever thread drains the engine's
progress (a comm thread, a scheduler idle cycle, or an explicit
``progress()`` pump in engine-only tests).

One client per engine: the engine keeps ONE handler per tag, so a
second ServeClient would silently detach the first's reply path —
construction raises instead, and :meth:`close` releases the tag (and
wakes any parked callers) so a successor can attach."""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ..comm import wire
from ..comm.engine import TAG_SERVE, TAG_SERVE_REPLY

__all__ = ["ServeClient", "ServeTimeout"]

_GUARDED_BY = {
    "ServeClient._replies": "_cond",
    "ServeClient._next_req": "_cond",
    "ServeClient._closed": "_cond",
}


class ServeTimeout(TimeoutError):
    """No reply from the session server within the deadline."""


class ServeClient:
    def __init__(self, ce, server_rank: int,
                 timeout: float = 30.0) -> None:
        self._ce = ce
        self._dst = int(server_rank)
        self._timeout = float(timeout)
        self._cond = threading.Condition()
        self._replies: Dict[int, Dict[str, Any]] = {}
        self._next_req = 0
        self._closed = False
        registered = getattr(ce, "tag_registered", None)
        if registered is not None and registered(TAG_SERVE_REPLY):
            raise RuntimeError(
                "TAG_SERVE_REPLY already has a handler on this engine: "
                "one ServeClient per engine (close() the previous "
                "client before constructing another)")
        ce.tag_register(TAG_SERVE_REPLY, self._on_reply)

    def close(self) -> None:
        """Detach from the engine: release the reply tag for a
        successor client and fail any calls still parked in
        :meth:`_call` (they raise instead of riding their timeout)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._ce.tag_unregister(TAG_SERVE_REPLY)

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _on_reply(self, src: int, payload: Any) -> None:
        try:
            msg = wire.parse_serve(payload)
        except ValueError:
            return
        with self._cond:
            self._replies[msg["req"]] = msg
            self._cond.notify_all()

    def _call(self, op: str, timeout: Optional[float] = None,
              **kw) -> Dict[str, Any]:
        if not self._ce.serve_to(self._dst):
            raise RuntimeError(
                f"rank {self._dst} did not negotiate the sv capability "
                f"(serve knob unset on one end)")
        with self._cond:
            if self._closed:
                raise RuntimeError("ServeClient is closed")
            self._next_req += 1
            req = self._next_req
        self._ce.send_am(self._dst, TAG_SERVE,
                         wire.serve_request(op, req, **kw))
        budget = timeout if timeout is not None else self._timeout
        with self._cond:
            self._cond.wait_for(
                lambda: req in self._replies or self._closed,
                timeout=budget)
            if req in self._replies:
                return self._replies.pop(req)
            if self._closed:
                raise RuntimeError(
                    f"ServeClient closed while op {op!r} was in flight")
            raise ServeTimeout(
                f"serve op {op!r} to rank {self._dst}: no reply "
                f"within {budget:.1f}s")

    # -- API ----------------------------------------------------------------
    def open_tenant(self, tenant: str, weight: Optional[int] = None,
                    quota_bytes: Optional[int] = None, max_pools: int = 0,
                    max_tasks: int = 0) -> Dict[str, Any]:
        msg = self._call("open", tenant=tenant, weight=weight,
                         quota_bytes=quota_bytes, max_pools=max_pools,
                         max_tasks=max_tasks)
        if not msg.get("ok"):
            raise RuntimeError(msg.get("error", "open_tenant failed"))
        return msg

    def submit(self, tenant: str, build: Callable[[], Any], *,
               nbytes: int = 0, ntasks: int = 1,
               name: Optional[str] = None) -> int:
        """Submit a pool-building callable; returns the server ticket.

        ``build`` travels pickled through the AM layer — it must be a
        module-level callable (the same constraint DTD closures over
        the wire already have).  Raises on rejection."""
        msg = self._call("submit", tenant=tenant, build=build,
                         nbytes=nbytes, ntasks=ntasks, name=name)
        if not msg.get("ok"):
            raise RuntimeError(msg.get("error", "submit rejected"))
        return int(msg["ticket"])

    def wait(self, ticket: int,
             timeout: Optional[float] = None) -> Dict[str, Any]:
        """Block until the submitted pool completes on the server."""
        msg = self._call("wait", timeout=timeout, ticket=ticket)
        if not msg.get("ok"):
            raise RuntimeError(msg.get("error", f"wait({ticket}) failed"))
        return msg

    def stats(self) -> Dict[str, Any]:
        msg = self._call("stats")
        if not msg.get("ok"):
            raise RuntimeError(msg.get("error", "stats failed"))
        return msg["stats"]
