"""Data collections & distributions (SURVEY.md §2.6)."""
from .collection import DataCollection, DictCollection, LocalArrayCollection
from .matrix import (SymTwoDimBlockCyclic, SymTwoDimBlockCyclicBand,
                     TiledMatrix, TwoDimBlockCyclic, TwoDimBlockCyclicBand,
                     TwoDimTabular, VectorTwoDimCyclic)
from .redistribute import redistribute, redistribute_ptg, reshard_array
from .subtile import SubtileView
from . import ops

__all__ = [
    "DataCollection", "DictCollection", "LocalArrayCollection", "TiledMatrix",
    "TwoDimBlockCyclic", "SymTwoDimBlockCyclic", "TwoDimBlockCyclicBand",
    "SymTwoDimBlockCyclicBand",
    "TwoDimTabular", "VectorTwoDimCyclic", "redistribute", "redistribute_ptg", "reshard_array",
    "ops", "SubtileView",
]
