#!/usr/bin/env python
"""Offline telemetry report: critical path, per-task-class breakdown,
and the T3-style compute/comm overlap fraction per rank.

Feed it the Chrome-trace JSON written at fini (``profile=<prefix>`` or
``Context(profile=True)`` + ``Profile.dump``) and, for the critical
path, the executed-DAG DOT (``profiling_dot=<prefix>``):

    python tools/obs_report.py /tmp/run.rank0.trace.json \\
        --dot /tmp/run.rank0.dot
    python tools/obs_report.py run.rank*.trace.json --json

Multiple rank traces merge into one report (ranks keyed by pid).

``--live SRC`` renders an obs_live health document instead — SRC is
either a running aggregator's URL (``http://host:port/health``) or a
saved snapshot JSON (per-rank or fleet) — through the same text/
``--json`` formatter, so online and offline reports stay one code path.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.obs import analyze, format_health, format_report  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("traces", nargs="*",
                    help="Chrome-trace JSON file(s), one per rank")
    ap.add_argument("--live", default=None, metavar="URL|SNAPSHOT",
                    help="render a live health document instead of "
                         "traces: an aggregator /health URL or a saved "
                         "snapshot JSON file")
    ap.add_argument("--dot", default=None,
                    help="executed-DAG DOT from the grapher "
                         "(enables the critical-path section)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw report as JSON instead of text")
    ap.add_argument("--tenant", default=None, metavar="NAME",
                    help="narrow the cross-rank section to flow edges a "
                         "serve/ SessionServer attributed to NAME (the "
                         "one-customer SLO view of a shared fleet)")
    ap.add_argument("--gate-overlap", type=float, default=None,
                    metavar="FRAC",
                    help="exit non-zero when any rank's compute/comm "
                         "overlap fraction is below FRAC (zero-comm "
                         "ranks report 1.0 and never trip the gate) — "
                         "the CI hook for the T3 overlap target")
    args = ap.parse_args(argv)

    if args.live is not None:
        if args.live.startswith("http"):
            import urllib.request
            url = args.live
            if not url.rstrip("/").endswith(("/health", "/timeline")):
                url = url.rstrip("/") + "/health"
            with urllib.request.urlopen(url, timeout=5) as resp:
                doc = json.loads(resp.read().decode())
        else:
            with open(args.live) as fh:
                doc = json.load(fh)
        if args.json:
            json.dump(doc, sys.stdout, indent=2)
            print()
        else:
            print(format_health(doc))
        return 0
    if not args.traces:
        ap.error("either trace files or --live is required")

    docs = []
    for path in args.traces:
        with open(path) as fh:
            docs.append(json.load(fh))
    dot_text = None
    if args.dot:
        with open(args.dot) as fh:
            dot_text = fh.read()

    report = analyze(docs, dot_text=dot_text, tenant=args.tenant)
    if args.json:
        json.dump(report, sys.stdout, indent=2, default=repr)
        print()
    else:
        print(format_report(report))
    if args.gate_overlap is not None:
        bad = {pid: ov["overlap_fraction"]
               for pid, ov in report.get("overlap", {}).items()
               if ov["overlap_fraction"] < args.gate_overlap}
        if bad:
            print(f"OVERLAP GATE FAILED: {len(bad)} rank(s) below "
                  f"{args.gate_overlap}: "
                  + ", ".join(f"rank {p}={f:.3f}"
                              for p, f in sorted(bad.items())),
                  file=sys.stderr)
            return 2
        print(f"overlap gate passed: every rank >= {args.gate_overlap}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
