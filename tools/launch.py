#!/usr/bin/env python
"""parsec_tpu process launcher — the mpiexec analog.

Spawns N SPMD rank processes of a user program, wiring each one's comm
engine via PARSEC_MCA_* env vars (the reference hands each process its
communicator through mpiexec + MPI_Init; here the launcher allocates the
control-plane endpoints and each rank's Context auto-builds a
TCPCommEngine + RemoteDepEngine at init, runtime/context.py
_comm_from_params). Ref: parsec/parsec_mpi_funnelled.c:245-365 (the
transport this replaces), SURVEY.md §5.8.

Usage:
  python tools/launch.py -n N [options] prog.py [prog args...]

Options:
  -n N                 number of ranks (default 2)
  --jax-distributed    also start a jax.distributed coordinator so the
                       ranks form ONE global jax device mesh (GSPMD
                       across processes); rank 0 hosts the coordinator
  --host H             bind host (default 127.0.0.1)
  --timeout S          per-rank wall clock limit (default 3600)
  --env K=V            extra env var for every rank (repeatable)

Multi-host (the thing mpiexec exists to do):
  --hosts H1,H2,...    place ranks round-robin on these hosts; each
                       rank's endpoint binds ITS host's real interface
                       and non-local ranks are spawned through --ssh
                       (`ssh Hk 'cd WORKDIR && env VARS python prog'`).
                       An entry is NAME[:BINDADDR] — ssh to NAME, bind
                       the endpoint on BINDADDR (management vs data
                       plane). Hosts named localhost/127.* spawn
                       directly.
  --ssh CMD            remote-spawn command (default "ssh"; any agent
                       that accepts `CMD host shell-command` works)
  --python EXE         remote interpreter (default: this one)
  --workdir DIR        remote working directory + PYTHONPATH (default:
                       this repo's root — assume a shared filesystem or
                       an identical checkout, like any MPI deployment)
  --port-base P        first control-plane port for --hosts runs
                       (default 28900; rank r listens on P+r, the jax
                       coordinator on P+N)

The v5p-style deployment recipe lives in docs/guide.md ("Multi-host
deployment").

Each rank's stdout/stderr is streamed line-by-line with a "[r]" prefix.
Exit status: 0 when every rank exits 0; otherwise the first non-zero
rank's status (remaining ranks are killed — fail fast, like mpiexec).
"""
import argparse
import os
import shlex
import signal
import subprocess
import sys
import threading

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_LOCAL_NAMES = ("localhost", "127.", "::1")


def _is_local(host: str) -> bool:
    return host == "" or host == "::1" or \
        any(host == n or host.startswith(n) for n in _LOCAL_NAMES)


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="launch.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-n", type=int, default=2, dest="nranks")
    ap.add_argument("--jax-distributed", action="store_true")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--timeout", type=float, default=3600.0)
    ap.add_argument("--env", action="append", default=[])
    ap.add_argument("--hosts", default=None)
    ap.add_argument("--ssh", default="ssh")
    ap.add_argument("--python", default=sys.executable)
    ap.add_argument("--workdir", default=ROOT)
    ap.add_argument("--port-base", type=int, default=28900)
    ap.add_argument("prog")
    ap.add_argument("prog_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()

    from parsec_tpu.comm.tcp import free_ports

    n = args.nranks
    if args.hosts:
        # each entry is NAME[:BINDADDR]: NAME is the --ssh target (the
        # management hostname), BINDADDR the data-plane interface the
        # rank's endpoint binds/advertises (defaults to NAME)
        hosts = []
        for h in args.hosts.split(","):
            h = h.strip()
            if h:
                name, _, bind = h.partition(":")
                hosts.append((name, bind or name))
        if not hosts:
            ap.error("--hosts: empty host list")
        host_of = [hosts[r % len(hosts)][0] for r in range(n)]
        bind_of = [hosts[r % len(hosts)][1] for r in range(n)]
        # remote hosts can't join a local free-port probe: fixed
        # port-base layout, unique per rank even when hosts repeat
        ports = [args.port_base + r for r in range(n + 1)]
    else:
        host_of = [args.host] * n
        bind_of = host_of
        ports = free_ports(n + (1 if args.jax_distributed else 0))
    endpoints = ",".join(f"{bind_of[r]}:{ports[r]}" for r in range(n))

    # vars the launcher wires (carried to remote ranks over --ssh; the
    # full local environ only reaches directly-spawned local ranks)
    wired = {}
    for kv in args.env:
        k, _, v = kv.partition("=")
        wired[k] = v
    wired["PARSEC_MCA_comm_transport"] = "tcp"
    wired["PARSEC_MCA_comm_endpoints"] = endpoints
    if args.jax_distributed:
        wired["PARSEC_MCA_jax_coordinator"] = f"{bind_of[0]}:{ports[n]}"
        wired["PARSEC_MCA_jax_num_processes"] = str(n)
    base_env = dict(os.environ)
    base_env.update(wired)

    procs = []
    for r in range(n):
        rank_over = {"PARSEC_MCA_comm_rank": str(r)}
        if args.jax_distributed:
            rank_over["PARSEC_MCA_jax_process_id"] = str(r)
        if args.hosts and not _is_local(host_of[r]):
            over = dict(wired)
            over.update(rank_over)
            over.setdefault("PYTHONPATH", args.workdir)
            parts = ["cd", shlex.quote(args.workdir), "&&", "env"]
            parts += [f"{k}={shlex.quote(v)}"
                      for k, v in sorted(over.items())]
            # resolve prog against the REMOTE workdir (the local
            # checkout path means nothing on the other machine);
            # absolute paths are taken as-is
            rprog = args.prog if os.path.isabs(args.prog) else \
                os.path.join(args.workdir, args.prog)
            parts += [shlex.quote(args.python), shlex.quote(rprog)]
            parts += [shlex.quote(a) for a in args.prog_args]
            cmd = shlex.split(args.ssh) + [host_of[r], " ".join(parts)]
            procs.append(subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        else:
            env = dict(base_env)
            env.update(rank_over)
            procs.append(subprocess.Popen(
                [sys.executable, args.prog] + args.prog_args,
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))

    def pump(r, stream):
        for line in stream:
            sys.stdout.write(f"[{r}] {line}")
            sys.stdout.flush()

    pumps = [threading.Thread(target=pump, args=(r, p.stdout), daemon=True)
             for r, p in enumerate(procs)]
    for t in pumps:
        t.start()

    rc = 0
    try:
        for r, p in enumerate(procs):
            try:
                p.wait(timeout=args.timeout)
            except subprocess.TimeoutExpired:
                sys.stderr.write(f"launch.py: rank {r} exceeded "
                                 f"{args.timeout}s; killing all\n")
                rc = rc or 124
                break
            if p.returncode != 0 and rc == 0:
                sys.stderr.write(f"launch.py: rank {r} exited "
                                 f"{p.returncode}; killing the rest\n")
                rc = p.returncode
                break
    except KeyboardInterrupt:
        rc = 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for t in pumps:
            t.join(timeout=2)
    if rc == 0 and any(p.returncode != 0 for p in procs):
        rc = next(p.returncode for p in procs if p.returncode != 0)
    return rc


if __name__ == "__main__":
    sys.exit(main())
