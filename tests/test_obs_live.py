"""Streaming health monitor (ISSUE 16, ``obs_live``): rolling-window
per-link/per-pool attribution, self-calibrated detectors, the online/
offline parity gate, the fleet-merged ``GET /health`` endpoint, and
knob-unset inertness.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.comm import LocalFabric, RemoteDepEngine
from parsec_tpu.comm.engine import TAG_ACTIVATE, FlowIds
from parsec_tpu.obs import (CommObs, LiveHealth, MetricsRegistry,
                            OBS_HEALTH_STATUS, OBS_HEALTH_STRAGGLER,
                            RollingStat, analyze, fleet_health,
                            flow_event_id, format_health,
                            merge_trace_docs)
from parsec_tpu.ops import dpotrf_taskpool, make_spd
from parsec_tpu.profiling.aggregator import AggregatorServer
from parsec_tpu.profiling.trace import Profile
from parsec_tpu.utils.params import params

from tests.conftest import spmd

US = 1000          # ns per µs
MS = 1_000_000     # ns per ms


# ---------------------------------------------------------------------- #
# RollingStat units                                                      #
# ---------------------------------------------------------------------- #
def test_rolling_stat_mean_z_percentile():
    st = RollingStat(alpha=0.5, ring=8)
    for v in (100.0, 100.0, 100.0, 100.0):
        st.push(v)
    assert st.mean == pytest.approx(100.0)
    # zero variance -> the 10%-of-mean floor, not a division by zero
    assert st.z(130.0) == pytest.approx(3.0)
    assert st.percentile(0.95) == 100.0
    for v in (90.0, 110.0):
        st.push(v)
    assert st.std() > 0
    assert st.z(st.mean) == pytest.approx(0.0)


def test_rolling_stat_all_zero_baseline_still_fires():
    """An idle link's baseline is all zeros (mean 0, var 0); the first
    real spike must read as infinitely surprising, not z=0."""
    st = RollingStat()
    for _ in range(6):
        st.push(0.0)
    assert st.z(0.0) == 0.0
    assert st.z(5000.0) == float("inf")
    assert st.z(-1.0) == float("-inf")


# ---------------------------------------------------------------------- #
# deterministic detectors (tick() driven directly, no monitor thread)    #
# ---------------------------------------------------------------------- #
def _steady_windows(lh, k, t0_ns=0, comm_us=1000):
    """k windows of a steady comm pattern on R1->R0, one tick each;
    returns the ns cursor after the last window."""
    t = t0_ns
    for _ in range(k):
        lh.note_comm(t, t + comm_us * US, src=1)
        lh.tick()
        t += 100 * MS
    return t


def test_straggler_fires_on_correct_link_and_suspect():
    lh = LiveHealth(0, warmup_windows=3, min_exposed_us=100.0)
    t = _steady_windows(lh, 6)
    # the spike: a 50 ms inbound wait in one window
    lh.note_comm(t, t + 50 * MS, src=1)
    fired = lh.tick()
    kinds = {f["kind"] for f in fired}
    assert "straggler" in kinds
    f = next(f for f in fired if f["kind"] == "straggler")
    assert f["link"] == "R1->R0" and f["suspect"] == 1
    assert f["rank"] == 0 and f["value"] > 10_000
    snap = lh.snapshot()
    assert snap["counts"]["straggler"] >= 1
    assert snap["status"] == 1
    assert snap["firings"][-1]["kind"] == "straggler"


def test_straggler_needs_warm_baseline_and_outbound_never_accuses():
    lh = LiveHealth(0, warmup_windows=3, min_exposed_us=100.0)
    # spike in window 1: baseline cold, nothing fires
    lh.note_comm(0, 50 * MS, src=1)
    assert lh.tick() == []
    # outbound exposure (dst=1) never accuses a peer
    lh2 = LiveHealth(0, warmup_windows=1, min_exposed_us=100.0)
    t = 0
    for _ in range(6):
        lh2.note_comm(t, t + 1 * MS, dst=1)
        lh2.tick()
        t += 100 * MS
    lh2.note_comm(t, t + 80 * MS, dst=1)
    assert all(f["kind"] != "straggler" for f in lh2.tick())
    # ...but the link still shows up in the exposure table
    assert "R0->R1" in lh2.snapshot()["per_link_exposed_us"]


def test_compute_hides_comm_from_the_exposure_table():
    """A comm span fully under compute is 100% overlapped — zero
    exposed, no straggler material (the offline per-interval algebra)."""
    lh = LiveHealth(0)
    lh.note_compute(0, 10 * MS)
    lh.note_comm(2 * MS, 6 * MS, src=1)
    snap = lh.snapshot()
    assert snap["per_link_exposed_us"] == {}
    assert snap["overlap"]["overlap_fraction"] == pytest.approx(1.0)
    # half-hidden: only the un-hidden tail is exposed
    lh.note_comm(8 * MS, 14 * MS, src=1)
    snap = lh.snapshot()
    assert snap["per_link_exposed_us"]["R1->R0"] == pytest.approx(
        4000.0, abs=1.0)


def test_degraded_link_lag_regression_and_offset_conversion():
    offsets = {1: 250.0}
    lh = LiveHealth(0, warmup_windows=3, min_lag_us=100.0,
                    clock_offset_fn=offsets.get)
    t = 0
    for _ in range(5):
        # 1 µs wire time + 250 µs offset = ~251 µs lag
        lh.note_flow_recv(1, 0, t, t + 1 * US)
        lh.tick()
        t += 100 * MS
    snap = lh.snapshot()
    assert snap["per_link_lag_us"]["R1->R0"]["ewma_us"] == pytest.approx(
        251.0, abs=1.0)
    # regression: 10x the EWMA in one window
    lh.note_flow_recv(1, 0, t, t + 2510 * US)
    fired = lh.tick()
    f = next(f for f in fired if f["kind"] == "degraded_link")
    assert f["link"] == "R1->R0"
    assert lh.snapshot()["counts"]["degraded_link"] == 1


def test_stuck_progress_fires_once_and_recovers():
    lh = LiveHealth(0, stuck_windows=3, pending_fn=lambda: 5)
    lh.note_compute(0, 1 * MS)          # some activity, then silence
    lh.tick()
    fired = []
    for _ in range(6):
        fired += lh.tick()
    stuck = [f for f in fired if f["kind"] == "stuck"]
    assert len(stuck) == 1, "one firing per stuck episode"
    assert lh.gauge_status() == 2
    # progress resumes -> status recovers (after the degraded tail)
    for i in range(8):
        lh.note_compute((10 + i) * MS, (11 + i) * MS)
        lh.tick()
    assert lh.gauge_status() in (0, 1)
    snap = lh.snapshot()
    assert snap["counts"]["stuck"] == 1


def test_exec_busy_collapse_accuses_self():
    lh = LiveHealth(3, warmup_windows=3, pending_fn=lambda: 2)
    t = 0
    for _ in range(6):
        lh.note_compute(t, t + 10 * MS)
        lh.tick()
        t += 100 * MS
    fired = []
    for _ in range(2):
        fired += lh.tick()          # busy collapses to 0 with pending
    f = next(f for f in fired if f["kind"] == "straggler")
    assert f["suspect"] == 3 and f["link"] is None


def test_degraded_link_bw_collapse():
    bw = {"v": 100.0}
    lh = LiveHealth(0, warmup_windows=3,
                    link_bw_fn=lambda peer: bw["v"])
    # the bw detector only polls links it has seen traffic on
    lh.note_comm(0, 1 * MS, src=1)
    for _ in range(5):
        lh.tick()
    bw["v"] = 10.0                  # collapses to 0.1x the EWMA
    fired = lh.tick()
    f = next(f for f in fired if f["kind"] == "degraded_link")
    assert f["link"] == "R0->R1" and f["value"] == pytest.approx(10.0)


# ---------------------------------------------------------------------- #
# trace annotations + memory bounds                                      #
# ---------------------------------------------------------------------- #
def test_firing_lands_as_instant_annotation_with_args():
    from parsec_tpu.obs.spans import HEALTH_STREAM_TID

    p = Profile(rank=0)
    lh = LiveHealth(0, warmup_windows=3, min_exposed_us=100.0,
                    stream=p.stream(HEALTH_STREAM_TID, "health"))
    t = _steady_windows(lh, 6)
    lh.note_comm(t, t + 50 * MS, src=1)
    assert lh.tick()
    doc = p.to_chrome_trace()
    inst = [e for e in doc["traceEvents"] if e.get("ph") == "i"]
    assert inst, "no instant annotation in the trace"
    ev = next(e for e in inst if e["name"] == "health:straggler")
    assert ev["args"]["link"] == "R1->R0"
    assert ev["args"]["suspect"] == 1
    assert ev["tid"] == HEALTH_STREAM_TID


def test_rolling_channels_stay_bounded():
    lh = LiveHealth(0)
    for i in range(3 * lh.COALESCE_AT):
        t = i * 100 * US
        lh.note_comm(t, t + 50 * US, src=1)
        if i % 2:
            lh.note_compute(t, t + 25 * US)
    with lh._lock:
        assert len(lh._comm) <= lh.COALESCE_AT + 1
        assert len(lh._compute) <= lh.COALESCE_AT + 1
    # sealed totals keep the aggregates whole
    snap = lh.snapshot()
    assert snap["overlap"]["comm_us"] == pytest.approx(
        3 * lh.COALESCE_AT * 50.0, rel=0.01)
    assert snap["per_link_exposed_us"]["R1->R0"] > 0


# ---------------------------------------------------------------------- #
# per-pool attribution through the extended flow context                 #
# ---------------------------------------------------------------------- #
def _live_pair():
    """Two local-fabric engines with flow + live armed on both ends
    (what the obs wiring does under ``obs_live``)."""
    fabric = LocalFabric(2)
    engines, lives, profiles = [], [], []
    for r in range(2):
        eng = fabric.engine(r)
        lh = LiveHealth(r)
        p = Profile(rank=r)
        eng._obs = CommObs(MetricsRegistry(), profile=p, live=lh)
        eng._flow = FlowIds(r)
        eng._flow.live = True
        engines.append(eng)
        lives.append(lh)
        profiles.append(p)
    return engines, lives, profiles


def test_pool_id_rides_the_flow_context():
    (e0, e1), (l0, l1), (p0, p1) = _live_pair()
    seen = []
    e1.tag_register(TAG_ACTIVATE, lambda src, pl: seen.append(pl))
    e0.send_am(1, TAG_ACTIVATE, {"tp_id": 7, "root": 0, "edges": {},
                                 "data": np.ones(4)})
    e1.progress()
    assert seen
    ctx = seen[0]["_tr"]
    assert len(ctx) == 4, "extended (origin, span, pool, t_send) context"
    assert ctx[2] == 7 and ctx[3] > 0
    # both halves attribute pool 7; flow ids still pair up
    assert l0.snapshot()["per_pool"]["7"]["sent"] == 1
    recv = l1.snapshot()["per_pool"]["7"]
    assert recv["recv"] == 1
    assert recv["lag_us_mean"] >= 0.0
    s_ev = [e for e in p0.to_chrome_trace()["traceEvents"]
            if e.get("ph") == "s"]
    f_ev = [e for e in p1.to_chrome_trace()["traceEvents"]
            if e.get("ph") == "f"]
    assert s_ev and f_ev and s_ev[0]["id"] == f_ev[0]["id"]
    assert s_ev[0]["id"] == flow_event_id(ctx)
    # the receiving link gained a lag sample on the live side
    with l1._lock:
        assert l1._lag_win.get("R0->R1")


def test_plain_flow_context_stays_two_tuple():
    """obs_flow WITHOUT obs_live: the wire context keeps the PR 15
    2-tuple — no pool id, no send timestamp, no extra bytes."""
    fabric = LocalFabric(2)
    e0, e1 = fabric.engine(0), fabric.engine(1)
    e0._obs = CommObs(MetricsRegistry(), profile=Profile(rank=0))
    e0._flow = FlowIds(0)           # live NOT armed
    seen = []
    e1.tag_register(TAG_ACTIVATE, lambda src, pl: seen.append(pl))
    e0.send_am(1, TAG_ACTIVATE, {"tp_id": 7, "edges": {}})
    e1.progress()
    assert seen and len(seen[0]["_tr"]) == 2


def test_tcp_live_negotiation_and_mixed_version_down():
    """Over real TCP: two obs_live peers negotiate "lv" and exchange
    4-tuple contexts; a mixed-version peer (knob unset) negotiates the
    sender all the way down — no stamp at all."""
    from parsec_tpu.comm.tcp import TCPCommEngine, free_ports

    def boot_pair(live0, live1):
        eps = [("127.0.0.1", p) for p in free_ports(2)]
        engines = [None, None]

        def boot(r, lv):
            engines[r] = TCPCommEngine(r, eps, obs_live=lv)
        ts = [threading.Thread(target=boot, args=(r, lv))
              for r, lv in ((0, live0), (1, live1))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        return engines

    # both live
    e0, e1 = boot_pair(True, True)
    try:
        lh = LiveHealth(0)
        e0._obs = CommObs(MetricsRegistry(), live=lh)
        e0._flow = FlowIds(0)
        e0._flow.live = True
        seen = []
        e1.tag_register(TAG_ACTIVATE, lambda src, pl: seen.append(pl))
        deadline = time.time() + 10
        while time.time() < deadline and not e0._peer_to(1).hello_seen:
            time.sleep(0.01)
        assert e0.live_to(1) and e0.flow_to(1)
        e0.send_am(1, TAG_ACTIVATE, {"tp_id": 3, "edges": {},
                                     "data": np.ones(4)})
        deadline = time.time() + 10
        while time.time() < deadline and not seen:
            e1.progress()
            time.sleep(0.005)
        assert seen and len(seen[0]["_tr"]) == 4
        assert seen[0]["_tr"][2] == 3
        assert lh.snapshot()["per_pool"]["3"]["sent"] == 1
    finally:
        e0.fini()
        e1.fini()

    # mixed version: the peer never advertised "lv" (nor "tr")
    e0, e1 = boot_pair(True, False)
    try:
        e0._obs = CommObs(MetricsRegistry(), live=LiveHealth(0))
        e0._flow = FlowIds(0)
        e0._flow.live = True
        seen = []
        e1.tag_register(TAG_ACTIVATE, lambda src, pl: seen.append(pl))
        deadline = time.time() + 10
        while time.time() < deadline and not e0._peer_to(1).hello_seen:
            time.sleep(0.01)
        assert not e0.live_to(1) and not e0.flow_to(1)
        e0.send_am(1, TAG_ACTIVATE, {"tp_id": 3, "edges": {},
                                     "data": np.ones(4)})
        deadline = time.time() + 10
        while time.time() < deadline and not seen:
            e1.progress()
            time.sleep(0.005)
        assert seen and "_tr" not in seen[0]
    finally:
        e0.fini()
        e1.fini()


def test_wire_capture_live_bit_identity():
    """The frame-level differential (dryrun gate leg): toward a peer
    that never advertised "lv", an obs_live sender's data frames are
    BIT-IDENTICAL to the knob-unset run."""
    import bench

    out = bench.bench_trace_capture_identity()
    assert out["trace_frames_captured"] > 0
    assert out["live_mixed_version_bit_identical"]


# ---------------------------------------------------------------------- #
# context wiring: knob-unset inertness, gauges, lifecycle                #
# ---------------------------------------------------------------------- #
def _live_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith("obs-live")]


def test_knob_unset_constructs_nothing():
    fab = LocalFabric(1)
    eng = RemoteDepEngine(fab.engine(0))
    ctx = parsec_tpu.Context(nb_cores=1, comm=eng)
    try:
        assert ctx.obs.live is None
        assert not _live_threads()
        assert OBS_HEALTH_STATUS not in ctx.sde.snapshot()
    finally:
        ctx.fini()


def test_knob_set_monitor_gauges_and_teardown():
    with params.cmdline_override("obs_live", "1"), \
            params.cmdline_override("obs_live_window_ms", "20"):
        fab = LocalFabric(1)
        eng = RemoteDepEngine(fab.engine(0))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng)
        try:
            assert ctx.obs.live is not None
            assert _live_threads() == ["obs-live-r0"]
            time.sleep(0.1)         # a few window ticks
            snap = ctx.sde.snapshot()
            assert snap[OBS_HEALTH_STATUS] == 0
            assert snap[OBS_HEALTH_STRAGGLER] == 0
            assert ctx.obs.live.counts["windows"] > 0
        finally:
            ctx.fini()
        assert not _live_threads(), "fini must stop the monitor"


# ---------------------------------------------------------------------- #
# online/offline parity gate (tier-1)                                    #
# ---------------------------------------------------------------------- #
def test_online_offline_parity_dpotrf():
    """The declared-tolerance gate: on a traced 2-rank dpotrf, the live
    aggregator's per-rank overlap fraction and per-link exposed-wait
    must match ``obs/critpath.analyze()`` over the SAME run's traces —
    one algebra, two evaluation times."""
    n, nb, ranks = 128, 32, 2
    M = make_spd(n, dtype=np.float32)
    with params.cmdline_override("obs_live", "1"), \
            params.cmdline_override("obs_flow", "1"), \
            params.cmdline_override("comm_mesh_local", "0"):
        def rank_fn(r, fab):
            eng = RemoteDepEngine(fab.engine(r))
            ctx = parsec_tpu.Context(nb_cores=1, comm=eng, profile=True)
            try:
                coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32,
                                         P=ranks, Q=1, nodes=ranks, rank=r)
                coll.name = "descA"
                coll.from_numpy(M.copy())
                ctx.add_taskpool(dpotrf_taskpool(coll, rank=r,
                                                 nb_ranks=ranks))
                ctx.wait()
                ctx._stamp_profile_meta()
                return (ctx.obs.live.snapshot(),
                        ctx.profile.to_chrome_trace())
            finally:
                ctx.fini()
        results, _fab = spmd(ranks, rank_fn)
    snaps = {r: results[r][0] for r in range(ranks)}
    report = analyze([merge_trace_docs([d for _s, d in results])])
    # -- overlap fraction: |live - offline| <= 0.10 per rank
    for r in range(ranks):
        live_ov = snaps[r]["overlap"]
        off_ov = report["overlap"][r]
        assert live_ov["overlap_fraction"] == pytest.approx(
            off_ov["overlap_fraction"], abs=0.10), f"rank {r}"
        # the raw comm seconds agree within 15%
        assert live_ov["comm_us"] == pytest.approx(
            off_ov["comm_us"], rel=0.15), f"rank {r}"
    # -- per-link exposed-wait: same links, each within 15% rel
    # (or 2 ms abs for near-zero entries)
    offline_links = report["cross_rank"]["per_link_exposed_us"]
    for r in range(ranks):
        live_links = snaps[r]["per_link_exposed_us"]
        for link, us in offline_links.get(r, {}).items():
            if us < 500:
                continue            # sub-noise entries prove nothing
            assert link in live_links, f"rank {r} missing {link}"
            assert live_links[link] == pytest.approx(
                us, rel=0.15, abs=2000.0), f"rank {r} {link}"
    # flow lag stitched live on the same links the offline report saw
    assert any(s["per_link_lag_us"] for s in snaps.values())


# ---------------------------------------------------------------------- #
# fleet merge, formatter, endpoints, chaos soak record                   #
# ---------------------------------------------------------------------- #
def _synthetic_snaps():
    lh0 = LiveHealth(0, warmup_windows=3, min_exposed_us=100.0)
    t = _steady_windows(lh0, 6)
    lh0.note_comm(t, t + 50 * MS, src=1)
    assert lh0.tick()
    lh1 = LiveHealth(1)
    lh1.note_comm(0, 2 * MS, src=0)
    lh1.tick()
    return lh0.snapshot(), lh1.snapshot()


def test_fleet_health_merges_and_ranks_worst_link():
    s0, s1 = _synthetic_snaps()
    doc = fleet_health({0: s0, 1: s1})
    assert doc["nb_ranks"] == 2
    assert doc["status"] == 1
    assert doc["counts"]["straggler"] >= 1
    assert doc["worst_link"]["link"] == "R1->R0"
    assert doc["firings"] == sorted(doc["firings"],
                                    key=lambda f: f["ts"])
    assert set(doc["ranks"]) == {"0", "1"}
    json.dumps(doc)                 # JSON-clean end to end
    # one formatter for both shapes
    txt = format_health(doc)
    assert "fleet of 2 rank(s)" in txt and "R1->R0" in txt
    assert "rank 0" in format_health(s0)


def test_health_and_timeline_endpoints():
    """The dryrun-gate surface: per-rank snapshots pushed to the
    aggregator come back fleet-merged over ``GET /health`` and as one
    time axis over ``GET /timeline``."""
    s0, s1 = _synthetic_snaps()
    srv = AggregatorServer().start()
    try:
        srv._ingest({"rank": 0, "counters": {}, "health": s0})
        srv._ingest({"rank": 1, "counters": {}, "health": s1})
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/health", timeout=5) as r:
            doc = json.loads(r.read().decode())
        assert doc["nb_ranks"] == 2
        assert doc["worst_link"]["link"] == "R1->R0"
        f = next(f for f in doc["firings"] if f["kind"] == "straggler")
        assert f["suspect"] == 1 and f["rank"] == 0
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/timeline", timeout=5) as r:
            tl = json.loads(r.read().decode())
        assert tl["nb_ranks"] == 2
        assert any(e["kind"] == "straggler" for e in tl["events"])
        ts = [e["ts"] for e in tl["events"]]
        assert ts == sorted(ts)
        srv.clear_health()
        assert srv.health_fleet()["nb_ranks"] == 0
    finally:
        srv.stop()


def test_sde_push_carries_health(tmp_path):
    """End to end over the push path: a context with obs_live + sde_push
    lands its snapshot on the aggregator without any HTTP client."""
    srv = AggregatorServer().start()
    try:
        with params.cmdline_override("obs_live", "1"), \
                params.cmdline_override("sde_push", srv.address), \
                params.cmdline_override("sde_push_interval_ms", "50"):
            fab = LocalFabric(1)
            eng = RemoteDepEngine(fab.engine(0))
            ctx = parsec_tpu.Context(nb_cores=1, comm=eng)
            ctx.fini()              # the stop-path push is guaranteed
        deadline = time.time() + 10
        while time.time() < deadline \
                and srv.health_fleet()["nb_ranks"] == 0:
            time.sleep(0.02)
        doc = srv.health_fleet()
        assert doc["nb_ranks"] == 1 and "0" in doc["ranks"]
    finally:
        srv.stop()


def test_chaos_soak_health_record(tmp_path):
    from tools.chaos_run import _append_health

    s0, s1 = _synthetic_snaps()
    srv = AggregatorServer()        # no network needed for the fold
    srv._ingest({"rank": 0, "counters": {}, "health": s0})
    srv._ingest({"rank": 1, "counters": {}, "health": s1})
    path = str(tmp_path / "health.jsonl")
    _append_health(path, srv, iteration=3, recovery_s=2.5, rc=0)
    with open(path) as fh:
        rec = json.loads(fh.readline())
    assert rec["iteration"] == 3 and rec["rc"] == 0
    assert rec["recovery_s"] == 2.5
    assert rec["nb_ranks"] == 2
    assert rec["straggler"] >= 1
    assert rec["worst_link"]["link"] == "R1->R0"
    assert rec["firing_events"]
    # the scrape cleared the fleet for the next iteration
    assert srv.health_fleet()["nb_ranks"] == 0
