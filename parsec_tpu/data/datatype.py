"""Datatype descriptors: dtype + shape + layout.

Reference behavior: ``parsec_datatype_t`` wraps MPI datatypes describing a
tile's memory layout (contiguous, vector/strided, triangular)
(ref: parsec/datatype/datatype_mpi.c:15-27, parsec/datatype.h).

TPU-native re-design: there is no wire datatype — data moves as device
arrays. A Datatype here is a (dtype, shape, region) descriptor used for
arena sizing, reshape decisions, and remote-edge type matching. ``region``
captures non-rectangular views (upper/lower triangle) that the reference
expressed as derived MPI types; conversion between regions is a compiled
XLA gather/where, performed by the reshape engine.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class Datatype:
    dtype: Any                    # numpy dtype-like
    shape: Tuple[int, ...]
    region: str = "full"          # "full" | "upper" | "lower" | "band"
    band: Optional[Tuple[int, int]] = None  # (kl, ku) when region == "band"

    @property
    def nb_elts(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def nbytes(self) -> int:
        return self.nb_elts * np.dtype(self.dtype).itemsize

    def contiguous(self) -> "Datatype":
        return Datatype(self.dtype, self.shape, "full")

    def compatible_wire(self, other: "Datatype") -> bool:
        """Same bytes-on-the-wire? (drives remote reshape decisions)."""
        return (np.dtype(self.dtype) == np.dtype(other.dtype)
                and self.shape == other.shape and self.region == other.region)

    def mask(self) -> Optional[np.ndarray]:
        """Boolean mask of the valid region (None == everything valid)."""
        if self.region == "full":
            return None
        assert len(self.shape) == 2, "regioned datatypes are 2-D"
        m, n = self.shape
        ii, jj = np.meshgrid(np.arange(m), np.arange(n), indexing="ij")
        if self.region == "upper":
            return jj >= ii
        if self.region == "lower":
            return jj <= ii
        if self.region == "band":
            kl, ku = self.band or (0, 0)
            return (jj - ii <= ku) & (ii - jj <= kl)
        raise ValueError(f"unknown region {self.region}")


def dtt_of_array(arr: Any, region: str = "full") -> Datatype:
    return Datatype(dtype=arr.dtype, shape=tuple(arr.shape), region=region)
