"""stagec/ — whole-stage DAG→XLA compilation (ISSUE 12).

Lower verified PTG stages into fused jitted programs: the lowerability
pass (:mod:`.plan`) partitions the instantiated DAG into compilable
stages vs interpreted residue using the analysis/ verdicts; the
lowering pass (:mod:`.lower`) emits one traced function per stage
(AOT-cached per spec/NB/dtype/stage shape); sharded variants
(:mod:`.sharded`) compile wave fronts through shard_map over the
rank's chip mesh; and the runtime integration (:mod:`.runtime`)
executes compiled stages as single chores interleaved with the
interpreted residue behind the ``stage_compile`` MCA knob.
"""
from .plan import (ClassVerdict, Stage, StagePlan, class_verdicts,
                   lower_report, plan_stages, stage_report)
from .lower import StageLayout, build_layout, build_stage_fn, spec_token
from .runtime import StageCompiler, prepared_plan, try_install
from .chain import ChainState, boundary_verdict, declare_chain

__all__ = [
    "ClassVerdict", "Stage", "StagePlan", "class_verdicts",
    "lower_report", "plan_stages", "stage_report", "StageLayout",
    "build_layout", "build_stage_fn", "spec_token", "StageCompiler",
    "prepared_plan", "try_install", "ChainState", "boundary_verdict",
    "declare_chain",
]
