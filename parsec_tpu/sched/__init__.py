"""Scheduler MCA framework: module registry + selection.

ref: mca_components_open_bytype / parsec_set_scheduler
(parsec/scheduling.c:246-272, parsec/mca/mca_repository.c).
"""
from __future__ import annotations

from typing import Dict, Type

from .base import SchedulerModule
from .modules import (APScheduler, GDScheduler, IPScheduler, LFQScheduler,
                      LHQScheduler, LLScheduler, LTQScheduler, PBQScheduler,
                      RNDScheduler, SPQScheduler)

from ..utils import mca

for _cls in (LFQScheduler, LHQScheduler, LTQScheduler, LLScheduler,
             GDScheduler, APScheduler, IPScheduler, SPQScheduler,
             PBQScheduler, RNDScheduler):
    mca.register("sched", _cls.name, _cls)

# kept for introspection/tests; the authoritative table is the MCA
# repository ("sched" framework — dotted paths and entry points load
# out-of-tree schedulers by name, mca_repository.c analog). Built from
# the static tuple: entry points stay LAZY (loaded only when selected)
_REGISTRY: Dict[str, Type[SchedulerModule]] = {
    cls.name: cls for cls in (
        LFQScheduler, LHQScheduler, LTQScheduler, LLScheduler, GDScheduler,
        APScheduler, IPScheduler, SPQScheduler, PBQScheduler, RNDScheduler)}


def sched_new(name: str) -> SchedulerModule:
    cls = mca.open_component("sched", name)
    if cls is None:
        # the reference's MCA select logs help and falls back to the
        # default component rather than failing init (scheduling.c:246-272)
        from ..utils.show_help import show_help
        show_help("help-runtime.txt", "unknown-scheduler", want_error=True,
                  name=name, available=", ".join(available()),
                  fallback="lfq")
        cls = mca.open_component("sched", "lfq")
    return cls()


def sched_register(cls: Type[SchedulerModule]) -> None:
    mca.register("sched", cls.name, cls)
    _REGISTRY[cls.name] = cls


def available() -> list:
    return mca.components("sched")
