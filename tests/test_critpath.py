"""obs/critpath: critical-path extraction, interval algebra, and the
compute/comm overlap fraction on deterministic synthetic traces."""
import pytest

from parsec_tpu.obs import analyze, critical_path, parse_dot
from parsec_tpu.obs.critpath import (load_trace_intervals, merge_intervals,
                                     overlap_us)


def _exec_span(pid, tid, cls, task, b, e):
    return [
        {"name": f"exec:{cls}", "ph": "B", "pid": pid, "tid": tid, "ts": b,
         "args": {"task": task}},
        {"name": f"exec:{cls}", "ph": "E", "pid": pid, "tid": tid, "ts": e},
    ]


def _comm_span(pid, b, e, name="comm:get"):
    return [
        {"name": name, "ph": "B", "pid": pid, "tid": 999, "ts": b},
        {"name": name, "ph": "E", "pid": pid, "tid": 999, "ts": e},
    ]


def _doc(events):
    return {"traceEvents": events, "metadata": {}}


def _dot(edges, nodes):
    lines = ["digraph dag {", "  node [style=filled];"]
    for nid, label in nodes.items():
        lines.append(f'  {nid} [label="{label}",fillcolor="#88CCEE",thid=0];')
    for a, b in edges:
        lines.append(f"  {a} -> {b};")
    lines.append("}")
    return "\n".join(lines)


def test_chain_critical_path_equals_total():
    """A pure chain has zero parallelism: the critical path IS the sum
    of every task's span time."""
    events = (_exec_span(0, 0, "STEP", "STEP(0)", 0, 100)
              + _exec_span(0, 0, "STEP", "STEP(1)", 100, 150)
              + _exec_span(0, 0, "STEP", "STEP(2)", 150, 175))
    dot = _dot([("STEP_0_", "STEP_1_"), ("STEP_1_", "STEP_2_")],
               {"STEP_0_": "STEP(0)", "STEP_1_": "STEP(1)",
                "STEP_2_": "STEP(2)"})
    report = analyze([_doc(events)], dot_text=dot)
    cp = report["critical_path"]
    assert cp["length_us"] == pytest.approx(175.0)
    assert cp["length_us"] == pytest.approx(cp["total_exec_us"])
    assert cp["tasks"] == ["STEP(0)", "STEP(1)", "STEP(2)"]
    assert cp["parallelism"] == pytest.approx(1.0)


def test_two_branch_critical_path_below_total():
    """root -> {b1, b2} -> join: the critical path takes the longer
    branch and is strictly below total exec time."""
    events = (_exec_span(0, 0, "R", "R(0)", 0, 10)
              + _exec_span(0, 0, "B", "B(1)", 10, 40)    # 30 us
              + _exec_span(0, 1, "B", "B(2)", 10, 30)    # 20 us
              + _exec_span(0, 0, "J", "J(0)", 40, 45))   # 5 us
    dot = _dot([("R_0_", "B_1_"), ("R_0_", "B_2_"),
                ("B_1_", "J_0_"), ("B_2_", "J_0_")],
               {"R_0_": "R(0)", "B_1_": "B(1)", "B_2_": "B(2)",
                "J_0_": "J(0)"})
    report = analyze([_doc(events)], dot_text=dot)
    cp = report["critical_path"]
    assert cp["length_us"] == pytest.approx(10 + 30 + 5)
    assert cp["tasks"] == ["R(0)", "B(1)", "J(0)"]
    assert cp["total_exec_us"] == pytest.approx(65.0)
    assert cp["length_us"] < cp["total_exec_us"]
    assert cp["parallelism"] > 1.0


def test_critical_path_rejects_cycles():
    with pytest.raises(ValueError, match="cycle"):
        critical_path({"a": 1.0, "b": 1.0}, [("a", "b"), ("b", "a")])


def test_parse_dot_grapher_format():
    from parsec_tpu.profiling.grapher import Grapher

    class _T:
        def __init__(self, label, tc):
            self._label, self.task_class = label, type("TC", (), {"name": tc})
        def snprintf(self):
            return self._label

    class _ES:
        th_id = 0

    g = Grapher()
    g.enable()
    g.task_executed(_ES(), _T("A(0)", "A"))
    g.task_executed(_ES(), _T("A(1)", "A"))
    g.dep(_T("A(0)", "A"), "A(1)", flow="X")
    labels, edges = parse_dot(g.to_dot())
    assert set(labels.values()) == {"A(0)", "A(1)"}
    assert edges == [("A(0)", "A(1)")]


def test_interval_algebra():
    assert merge_intervals([(0, 10), (5, 20), (30, 40)]) == [(0, 20), (30, 40)]
    assert merge_intervals([]) == []
    assert overlap_us([(0, 100)], [(50, 150)]) == pytest.approx(50.0)
    assert overlap_us([(0, 10), (20, 30)], [(5, 25)]) == pytest.approx(10.0)
    assert overlap_us([(0, 10)], [(20, 30)]) == 0.0


def test_overlap_fraction_per_rank():
    """Comm half-hidden under compute -> fraction 0.5; a second rank
    with fully exposed comm -> fraction 0.0."""
    events = (_exec_span(0, 0, "K", "K(0)", 0, 100)
              + _comm_span(0, 50, 150)
              + _exec_span(1, 0, "K", "K(1)", 0, 100)
              + _comm_span(1, 100, 200))
    report = analyze([_doc(events)])
    assert report["overlap"][0]["overlap_fraction"] == pytest.approx(0.5)
    assert report["overlap"][0]["comm_us"] == pytest.approx(100.0)
    assert report["overlap"][1]["overlap_fraction"] == pytest.approx(0.0)
    # per-class breakdown is keyed by rank then class
    assert report["by_class"][0]["K"]["count"] == 1
    assert report["by_class"][0]["K"]["total_us"] == pytest.approx(100.0)


def test_unmatched_events_are_dropped():
    """A stray E without B (or truncated B) must not produce intervals."""
    events = [{"name": "exec:X", "ph": "E", "pid": 0, "tid": 0, "ts": 5.0}]
    assert load_trace_intervals(_doc(events)) == []
