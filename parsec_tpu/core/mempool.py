"""Mempool — per-thread freelists for frequently recycled objects.

Reference behavior: ``parsec_mempool_t`` / ``parsec_thread_mempool_t``
give each execution stream a private freelist of fixed-size elements
(task structs, remote-dep structs); allocation pops locally without
contention and elements return to the thread that owns them
(ref: parsec/mempool.c/.h, parsec/private_mempool.c — SURVEY.md §2.1).

TPU-native re-design: Python task objects are interpreter-managed, so
the pool's job here is recycling *expensive payloads* — host scratch
buffers (DTD SCRATCH params), pinned staging arrays, reusable tile
temporaries. Same structure as the reference: a ``Mempool`` owns one
``ThreadMempool`` per thread (created on first touch, like
parsec_mempool_construct's per-ES array); ``allocate`` pops the calling
thread's freelist or constructs; ``free`` pushes back to the *owning*
thread's list (elements remember their owner, the
``parsec_thread_mempool_t *owner`` back-pointer)."""
from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, List, Optional

from ..utils import logging as plog

__all__ = ["Mempool", "ThreadMempool"]

# intrusive owner back-pointer (the reference's parsec_thread_mempool_t
# *owner field); set on the element itself so dropped elements carry no
# pool-side state
_OWNER_ATTR = "_parsec_mempool_owner"


def _drop_gauges(gauges: List[tuple]) -> None:
    """Unregister a named pool's SDE gauges (finalizer-safe: must not
    reference the pool). Passes each registered poll fn so a LIVE
    same-named pool's re-registration is left untouched (the identity
    guard SDERegistry.unregister exists for)."""
    from ..profiling.sde import sde
    for name, fn in gauges:
        sde.unregister(name, fn)


def _purge_owner(pool_ref: "weakref.ref", key: int) -> None:
    """weakref.finalize callback: drop a dead element's id entry without
    retaining the pool (a bound-method callback would keep the whole pool
    and its cached buffers alive for as long as any escaped element is)."""
    pool = pool_ref()
    if pool is not None:
        pool.owner_of.pop(key, None)


class ThreadMempool:
    """One thread's freelist (ref: parsec_thread_mempool_t)."""

    def __init__(self, pool: "Mempool", thread_id: int) -> None:
        self.pool = pool
        self.thread_id = thread_id
        self._free: List[Any] = []
        self._lock = threading.Lock()  # frees may come from other threads
        self.nb_elt = 0                # total constructed by this thread

    def allocate(self) -> Any:
        pool = self.pool
        with self._lock:
            if self._free:
                pool._note_alloc(hit=True)
                return self._free.pop()
            self.nb_elt += 1  # under the lock: free() races from other threads
        pool._note_alloc(hit=False)
        elt = pool.constructor()
        pool._set_owner(elt, self)
        return elt

    def push(self, elt: Any) -> None:
        with self._lock:
            if self.pool.max_cached < 0 or len(self._free) < self.pool.max_cached:
                self._free.append(elt)
            else:
                self.pool._disown(elt)  # dropped to GC: a stray later
                # free() must not re-insert it

    def __len__(self) -> int:
        with self._lock:
            return len(self._free)


class Mempool:
    """ref: parsec_mempool_t — a set of per-thread freelists sharing one
    constructor. ``max_cached`` bounds each thread's retained elements
    (-1 = unbounded, the reference default)."""

    def __init__(self, constructor: Callable[[], Any],
                 max_cached: int = -1, name: Optional[str] = None) -> None:
        self.constructor = constructor
        self.max_cached = max_cached
        self.owner_of: Dict[int, ThreadMempool] = {}
        self._threads: Dict[int, ThreadMempool] = {}
        self._lock = threading.Lock()
        # telemetry: allocation counters + outstanding high-water (plain
        # GIL int adds on the hot path, like sde.inc); a *named* pool
        # additionally exports pull gauges under PARSEC::MEMPOOL::<NAME>
        self.name = name
        self.nb_allocs = 0       # total allocate() calls
        self.nb_hits = 0         # served from a freelist (no construction)
        self.nb_outstanding = 0  # allocated minus freed
        self.outstanding_hwm = 0
        # fired (no args) after each free() returns an element, i.e.
        # whenever nb_outstanding drops — quota consumers (serve/
        # admission) re-evaluate queued work on it; must be cheap and
        # must not raise (failures are logged and swallowed)
        self.on_free: Optional[Callable[[], None]] = None
        self._gauges: List[tuple] = []  # (name, poll fn) for unregister
        if name:
            self._register_gauges(name)

    def _note_alloc(self, hit: bool) -> None:
        self.nb_allocs += 1
        if hit:
            self.nb_hits += 1
        n = self.nb_outstanding = self.nb_outstanding + 1
        if n > self.outstanding_hwm:
            self.outstanding_hwm = n

    def _register_gauges(self, name: str) -> None:
        """Export this pool's accounting on the process-wide SDE registry
        (contextless, like the reference's process-global counters).

        The poll closures hold only a WEAK reference to the pool — a
        strong one would pin every cached buffer for the process
        lifetime (the exact leak the _purge_owner docstring warns
        about) — and a finalizer drops the gauge names once the pool is
        collected, so abandoned pools clean up after themselves.
        ``unregister_gauges()`` does it eagerly."""
        from ..profiling.sde import sde
        prefix = f"PARSEC::MEMPOOL::{name.upper()}"
        ref = weakref.ref(self)

        def poll(attr: str):
            def fn():
                pool = ref()
                if pool is None:
                    return None
                v = getattr(pool, attr)
                return v() if callable(v) else v
            return fn

        self._gauges = []
        for suffix, attr in (("ALLOCS", "nb_allocs"), ("HITS", "nb_hits"),
                             ("OUTSTANDING", "nb_outstanding"),
                             ("OUTSTANDING_HWM", "outstanding_hwm"),
                             ("CACHED", "nb_cached"),
                             ("CONSTRUCTED", "nb_constructed")):
            gname = f"{prefix}::{suffix}"
            fn = poll(attr)
            sde.register_poll(gname, fn)
            self._gauges.append((gname, fn))
        weakref.finalize(self, _drop_gauges, list(self._gauges))

    def unregister_gauges(self) -> None:
        """Eagerly drop this pool's gauges from the global registry
        (also happens automatically when the pool is collected)."""
        _drop_gauges(self._gauges)
        self._gauges = []

    def thread_mempool(self, thread_id: Optional[int] = None) -> ThreadMempool:
        tid = thread_id if thread_id is not None else threading.get_ident()
        tm = self._threads.get(tid)
        if tm is None:
            with self._lock:
                tm = self._threads.setdefault(tid, ThreadMempool(self, tid))
        return tm

    def allocate(self) -> Any:
        return self.thread_mempool().allocate()

    def _set_owner(self, elt: Any, tm: ThreadMempool) -> None:
        """Record which thread-pool constructed ``elt``.

        Preferred: an attribute on the element itself (the reference's
        intrusive owner back-pointer). Objects that reject attributes
        (numpy arrays, slotted classes) fall back to an id-keyed map whose
        entry a weakref finalizer purges when the element dies — so ids
        reused after GC can't alias a foreign object into the pool.
        """
        try:
            setattr(elt, _OWNER_ATTR, tm)
            return
        except (AttributeError, TypeError):
            pass
        key = id(elt)
        self.owner_of[key] = tm
        try:
            weakref.finalize(elt, _purge_owner, weakref.ref(self), key)
        except TypeError:
            # supports neither attributes nor weakrefs (object(), tuples):
            # the entry is purged when push() drops the element, but an
            # element the USER drops without free() leaves a stale id that
            # a later id-reuse could alias — use an attr- or
            # weakref-capable element type if elements may leak
            pass

    def _disown(self, elt: Any) -> None:
        """Sever ownership of a dropped element (both carrier forms)."""
        try:
            delattr(elt, _OWNER_ATTR)
            return
        except AttributeError:
            pass
        self.owner_of.pop(id(elt), None)

    def free(self, elt: Any) -> None:
        """Return ``elt`` to its owning thread's freelist (the reference's
        elements carry an owner back-pointer; cross-thread frees land in
        the owner's list, not the caller's)."""
        owner = getattr(elt, _OWNER_ATTR, None)
        if owner is None:
            owner = self.owner_of.get(id(elt))
        if owner is not None:
            self.nb_outstanding = max(0, self.nb_outstanding - 1)
            owner.push(elt)
            cb = self.on_free
            if cb is not None:
                try:
                    cb()
                except Exception as exc:  # noqa: BLE001 - never kill free
                    plog.warning("mempool %s: on_free hook failed: %r",
                                 self.name or "<anon>", exc)
        # unknown element: not pool-constructed; drop it (GC)

    def nb_cached(self) -> int:
        with self._lock:
            return sum(len(tm) for tm in self._threads.values())

    def nb_constructed(self) -> int:
        with self._lock:
            return sum(tm.nb_elt for tm in self._threads.values())
