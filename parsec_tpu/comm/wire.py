"""Wire framing for the TCP transport: the comm-engine fast path.

Frame format (v2). Every frame on a connection is::

    <u64 body_len> <body>

``body_len == GOODBYE`` (2**64-1) is the clean-shutdown sentinel (no
body follows). Otherwise the body's first byte is a *kind*:

- ``K_BATCH``: one or more complete active messages coalesced into a
  single frame (ONE syscall per batch on the send side). Each message
  segment is ``<u32 pickle_len> <u32 nbufs> [<u64 size>]*nbufs
  <pickle> <buf bytes>*`` — the pickle-5 frame plus its out-of-band
  buffers, copied in-band at enqueue time (all are below the chunk
  threshold by construction, so the copy is small and preserves the
  historical copy-at-send snapshot semantics).
- ``K_XFER_HDR``: header of a chunked message — a message whose
  payload carries at least one buffer >= the chunk threshold. The
  pickle frame and the small buffers ride in the header; each large
  buffer is announced (size only) and its bytes follow as ``K_CHUNK``
  frames, interleavable with control traffic.
- ``K_CHUNK``: one bounded segment of one announced buffer
  (``<u64 xfer_id> <u32 buf_index> <u64 offset> <bytes>``). The
  receiver reassembles; the message is delivered when every announced
  byte has landed. Chunks of one transfer are FIFO; *other* frames may
  interleave between them — that is the point (no head-of-line
  blocking of small control AMs behind a multi-MB payload).
- ``K_HELLO``: capability advertisement sent once per connection right
  after the rank handshake (``{"ver", "codecs", "rank"}``). A peer
  that never sends one (mixed version) simply never negotiates a
  codec, so compression silently stays off toward it.
- ``K_COMP``: a compressed *body* (kind byte included) of any of the
  above: ``<u8 codec_id> <u64 raw_len> <compressed>``. Only emitted
  toward peers that advertised the codec.
- ``K_ELASTIC``: one elastic-membership message (ft/elastic.py — grid
  resize views, join announcements, welcomes) as a pickled dict.
  Handled directly by the receiver THREAD like ``K_PING``: a joiner's
  announcement or a resize proposal must land even while every worker
  is stuck in a long kernel. Only sent toward peers whose HELLO
  advertised ``"el"`` — a pre-elastic peer is never drawn into a
  resize agreement it cannot answer.
- ``K_PING`` / ``K_PONG``: heartbeat probe and its echo
  (``<u32 seq> <u64 t_ns>``, the sender's monotonic clock — the pong
  echoes it back so the sender computes the round trip). Handled
  directly by the receiver THREAD (like K_HELLO), never queued through
  the inbox: a rank stuck in a long kernel still answers, so TCP
  liveness judgment (ft/detector.py) is independent of the progress
  cadence. Only sent toward peers whose HELLO advertised ``"hb"`` — a
  mixed-version peer is never probed and therefore never declared dead
  by the proactive detector.

Reliable-session framing (the ``"rs"`` HELLO capability — transient
link faults recover by reconnect + replay instead of rank eviction,
comm/tcp.py):

- ``K_SEQ``: envelope around any DATA frame body (``<u32 epoch>
  <u64 seq> <inner body>``). Each direction numbers its data frames
  (batches, transfer headers, chunks) with a per-link monotonically
  increasing ``seq``; the receiver delivers in order exactly once —
  a replayed frame it already delivered is dropped by seq (idempotent
  re-delivery: no active message ever runs twice). Session-less
  control frames (hello, ping/pong, ack, resume, elastic) are never
  wrapped: they are regenerated, not replayed.
- ``K_ACK``: cumulative delivery acknowledgment (``<u32 epoch>
  <u64 seq>``) — everything up to ``seq`` landed, so the sender may
  drop those frames from its bounded replay window.
- ``K_RESUME``: reconnect handshake (a pickled dict), sent right
  after the rank-identifying handshake on a RE-dialed connection:
  carries the proposed session ``epoch``, the last-delivered ``ack``
  both ways, and optionally a ``partial`` claim — how many bytes of
  the next expected frame already landed before the link tore, so the
  sender resumes that frame mid-body instead of resending it.
- ``K_FRAG``: the byte-level resume of one torn frame
  (``<u32 epoch> <u64 seq> <u64 offset> <bytes>``): the remainder of
  the frame the receiver holds a partial body of; receiver stitches
  partial + remainder and dispatches the whole as a normal K_SEQ
  frame.

All integers little-endian, matching the v1 framing.
"""
from __future__ import annotations

import pickle
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

GOODBYE = (1 << 64) - 1  # frame-size sentinel: clean shutdown, not a crash

K_BATCH = 0
K_XFER_HDR = 1
K_CHUNK = 2
K_HELLO = 3
K_COMP = 4
K_PING = 5
K_PONG = 6
K_ELASTIC = 7
K_SEQ = 8
K_ACK = 9
K_RESUME = 10
K_FRAG = 11

WIRE_VERSION = 2

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_SEG = struct.Struct("<II")          # pickle_len, nbufs
_BATCH = struct.Struct("<BI")        # kind, nmsgs
_XFER = struct.Struct("<BQII")       # kind, xfer_id, pickle_len, nbufs
_BUFSPEC = struct.Struct("<BQ")      # chunked?, size
_CHUNK = struct.Struct("<BQIQ")      # kind, xfer_id, buf_index, offset
_COMP = struct.Struct("<BBQ")        # kind, codec_id, raw_len
_PING = struct.Struct("<BIQ")        # kind, seq, t_ns (sender monotonic)
_SEQHDR = struct.Struct("<BIQ")      # kind, epoch, seq (K_SEQ / K_ACK)
_FRAGHDR = struct.Struct("<BIQQ")    # kind, epoch, seq, byte offset


# -- codecs -------------------------------------------------------------
def _lz4_mod():
    try:
        import lz4.frame as _lz4
        return _lz4
    except ImportError:
        return None


#: name -> (wire id, compress, decompress); lz4 is optional — absent
#: installs simply don't advertise it at the handshake
CODECS: Dict[str, Tuple[int, Any, Any]] = {
    "zlib": (1, lambda b: zlib.compress(b, 1), zlib.decompress),
}
if _lz4_mod() is not None:  # pragma: no cover - env without lz4
    _l = _lz4_mod()
    CODECS["lz4"] = (2, _l.compress, _l.decompress)

_CODEC_BY_ID = {cid: (name, comp, dec)
                for name, (cid, comp, dec) in CODECS.items()}

#: preference order when both ends support several
_CODEC_PREF = ("lz4", "zlib")


def available_codecs() -> List[str]:
    return sorted(CODECS)


def negotiate_codec(mine: Sequence[str],
                    theirs: Sequence[str]) -> Optional[str]:
    """Pick the preferred codec both ends advertised (None: no common
    codec — e.g. a mixed-version peer that never sent a HELLO)."""
    common = set(mine) & set(theirs)
    for name in _CODEC_PREF:
        if name in common:
            return name
    return sorted(common)[0] if common else None


# -- message segments (K_BATCH) -----------------------------------------
def pack_segment(frame: bytes, bufs: Sequence[Any]) -> bytes:
    """One in-band message segment: pickle frame + copied buffers."""
    parts = [_SEG.pack(len(frame), len(bufs))]
    parts += [_U64.pack(len(b) if isinstance(b, (bytes, bytearray))
                        else b.nbytes) for b in bufs]
    parts.append(frame)
    parts += [bytes(b) for b in bufs]
    return b"".join(parts)


def pack_batch(segments: Sequence[bytes]) -> List[bytes]:
    """Body pieces of a K_BATCH frame holding ``segments`` messages."""
    return [_BATCH.pack(K_BATCH, len(segments)), *segments]


def parse_batch(body: memoryview) -> Iterator[Tuple[memoryview,
                                                    List[memoryview]]]:
    """Yield (pickle_frame, [buffers]) per coalesced message. The
    yielded views alias ``body`` — zero extra copy on the receive
    side; arrays reconstructed over them are read-only."""
    _kind, nmsgs = _BATCH.unpack_from(body, 0)
    off = _BATCH.size
    for _ in range(nmsgs):
        flen, nbufs = _SEG.unpack_from(body, off)
        off += _SEG.size
        sizes = [_U64.unpack_from(body, off + 8 * i)[0]
                 for i in range(nbufs)]
        off += 8 * nbufs
        frame = body[off:off + flen]
        off += flen
        bufs = []
        for sz in sizes:
            bufs.append(body[off:off + sz])
            off += sz
        yield frame, bufs
    if off != len(body):
        raise ValueError(
            f"batch frame desync: parsed {off} of {len(body)} bytes")


# -- chunked transfers (K_XFER_HDR / K_CHUNK) ---------------------------
def pack_xfer_hdr(xfer_id: int, frame: bytes,
                  bufspecs: Sequence[Tuple[bool, int, Optional[Any]]]
                  ) -> bytes:
    """Header of a chunked message. ``bufspecs``: per pickle-5 buffer,
    (chunked, size, inline_bytes-or-None) in buffer order; chunked
    buffers announce size only, their bytes follow as K_CHUNK frames."""
    parts = [_XFER.pack(K_XFER_HDR, xfer_id, len(frame), len(bufspecs))]
    parts += [_BUFSPEC.pack(1 if chunked else 0, size)
              for (chunked, size, _b) in bufspecs]
    parts.append(frame)
    parts += [bytes(b) for (chunked, _s, b) in bufspecs if not chunked]
    return b"".join(parts)


def parse_xfer_hdr(body: memoryview) -> Tuple[int, memoryview,
                                              List[Tuple[bool, int,
                                                         Optional[memoryview]]]]:
    _kind, xfer_id, flen, nbufs = _XFER.unpack_from(body, 0)
    off = _XFER.size
    specs = []
    for i in range(nbufs):
        chunked, size = _BUFSPEC.unpack_from(body, off)
        specs.append([bool(chunked), size, None])
        off += _BUFSPEC.size
    frame = body[off:off + flen]
    off += flen
    for spec in specs:
        if not spec[0]:
            spec[2] = body[off:off + spec[1]]
            off += spec[1]
    if off != len(body):
        raise ValueError(
            f"xfer header desync: parsed {off} of {len(body)} bytes")
    return xfer_id, frame, [tuple(s) for s in specs]


def pack_chunk_hdr(xfer_id: int, buf_index: int, offset: int) -> bytes:
    return _CHUNK.pack(K_CHUNK, xfer_id, buf_index, offset)


def parse_chunk(body: memoryview) -> Tuple[int, int, int, memoryview]:
    _kind, xfer_id, buf_index, offset = _CHUNK.unpack_from(body, 0)
    return xfer_id, buf_index, offset, body[_CHUNK.size:]


class RxXfer:
    """Receive-side reassembly of one chunked message."""

    __slots__ = ("frame", "bufs", "remaining", "nbytes")

    def __init__(self, frame: memoryview,
                 bufspecs: Sequence[Tuple[bool, int, Optional[memoryview]]]
                 ) -> None:
        # the pickle frame must outlive the enclosing frame body
        self.frame = bytes(frame)
        self.bufs: List[Any] = []
        self.remaining = 0
        self.nbytes = len(self.frame)
        for (chunked, size, inline) in bufspecs:
            self.nbytes += size
            if chunked:
                self.bufs.append(bytearray(size))
                self.remaining += size
            else:
                self.bufs.append(bytes(inline))

    def feed(self, buf_index: int, offset: int, data: memoryview) -> bool:
        """Land one chunk; True when the whole message has arrived."""
        buf = self.bufs[buf_index]
        if not isinstance(buf, bytearray):
            raise ValueError(f"chunk for non-chunked buffer {buf_index}")
        n = len(data)
        if offset + n > len(buf):
            raise ValueError(
                f"chunk overruns buffer {buf_index}: "
                f"{offset}+{n} > {len(buf)}")
        buf[offset:offset + n] = data
        self.remaining -= n
        return self.remaining <= 0

    def message(self) -> Any:
        return pickle.loads(self.frame, buffers=self.bufs)


def load_message(frame: memoryview, bufs: Sequence[Any]) -> Any:
    """Unpickle one (src, tag, payload) message segment."""
    return pickle.loads(frame, buffers=list(bufs))


# -- heartbeats (ft/detector.py) ----------------------------------------
def pack_ping(seq: int, t_ns: int, pong: bool = False) -> bytes:
    """One heartbeat frame; the pong echoes the ping's (seq, t_ns)."""
    return _PING.pack(K_PONG if pong else K_PING, seq & 0xFFFFFFFF, t_ns)


def parse_ping(body: memoryview) -> Tuple[int, int]:
    """-> (seq, t_ns); same layout for K_PING and K_PONG."""
    _kind, seq, t_ns = _PING.unpack_from(body, 0)
    return seq, t_ns


# -- reliable session (comm/tcp.py "rs" capability) ---------------------
SEQ_HDR_LEN = _SEQHDR.size


def pack_seq(epoch: int, seq: int) -> bytes:
    """Envelope header prepended to one data frame body."""
    return _SEQHDR.pack(K_SEQ, epoch & 0xFFFFFFFF, seq)


def parse_seq(body: memoryview) -> Tuple[int, int, memoryview]:
    """-> (epoch, seq, inner body)."""
    _kind, epoch, seq = _SEQHDR.unpack_from(body, 0)
    return epoch, seq, body[_SEQHDR.size:]


def parse_seq_prefix(buf) -> Optional[Tuple[int, int]]:
    """(epoch, seq) when ``buf`` begins with a complete K_SEQ header
    (the partial-frame resume claim), else None."""
    if len(buf) < _SEQHDR.size or buf[0] != K_SEQ:
        return None
    _kind, epoch, seq = _SEQHDR.unpack_from(buf, 0)
    return epoch, seq


def pack_ack(epoch: int, seq: int) -> bytes:
    """Cumulative ack: every seq up to ``seq`` was delivered."""
    return _SEQHDR.pack(K_ACK, epoch & 0xFFFFFFFF, seq)


def parse_ack(body: memoryview) -> Tuple[int, int]:
    _kind, epoch, seq = _SEQHDR.unpack_from(body, 0)
    return epoch, seq


def pack_resume(info: Dict[str, Any]) -> bytes:
    """Reconnect handshake frame ({"rank", "epoch", "ack", "partial"})."""
    return bytes([K_RESUME]) + pickle.dumps(info, protocol=4)


def parse_resume(body: memoryview) -> Dict[str, Any]:
    return pickle.loads(body[1:])


def pack_frag(epoch: int, seq: int, offset: int) -> bytes:
    """Header of a byte-level frame resume (remainder bytes follow)."""
    return _FRAGHDR.pack(K_FRAG, epoch & 0xFFFFFFFF, seq, offset)


def parse_frag(body: memoryview) -> Tuple[int, int, int, memoryview]:
    _kind, epoch, seq, offset = _FRAGHDR.unpack_from(body, 0)
    return epoch, seq, offset, body[_FRAGHDR.size:]


# -- elastic membership (ft/elastic.py) ---------------------------------
def pack_elastic(payload: Dict[str, Any]) -> bytes:
    """One membership frame (view / join / welcome dict)."""
    return bytes([K_ELASTIC]) + pickle.dumps(payload, protocol=4)


def parse_elastic(body: memoryview) -> Dict[str, Any]:
    return pickle.loads(body[1:])


# -- hello / compression ------------------------------------------------
def pack_hello(info: Dict[str, Any]) -> bytes:
    return bytes([K_HELLO]) + pickle.dumps(info, protocol=4)


def parse_hello(body: memoryview) -> Dict[str, Any]:
    return pickle.loads(body[1:])


def compress_body(body: bytes, codec: str) -> Optional[List[bytes]]:
    """K_COMP pieces for ``body``, or None when compression does not
    pay (the compressed form is not smaller)."""
    cid, comp, _dec = CODECS[codec]
    out = comp(body)
    if len(out) + _COMP.size >= len(body):
        return None
    return [_COMP.pack(K_COMP, cid, len(body)), out]


def decompress_body(body: memoryview) -> bytes:
    _kind, cid, raw_len = _COMP.unpack_from(body, 0)
    ent = _CODEC_BY_ID.get(cid)
    if ent is None:
        raise ValueError(f"unknown compression codec id {cid}")
    out = ent[2](bytes(body[_COMP.size:]))
    if len(out) != raw_len:
        raise ValueError(
            f"decompressed length {len(out)} != announced {raw_len}")
    return out
