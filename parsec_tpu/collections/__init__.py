"""Data collections & distributions (SURVEY.md §2.6)."""
from .collection import DataCollection, DictCollection, LocalArrayCollection
from .matrix import (SymTwoDimBlockCyclic, TiledMatrix, TwoDimBlockCyclic,
                     TwoDimBlockCyclicBand, TwoDimTabular, VectorTwoDimCyclic)

__all__ = [
    "DataCollection", "DictCollection", "LocalArrayCollection", "TiledMatrix",
    "TwoDimBlockCyclic", "SymTwoDimBlockCyclic", "TwoDimBlockCyclicBand",
    "TwoDimTabular", "VectorTwoDimCyclic",
]
