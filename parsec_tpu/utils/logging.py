"""Verbosity-leveled debug output streams.

Reference behavior: ``parsec_debug_verbose(level, stream, fmt...)`` with
per-subsystem output streams and global verbosity, plus warning/inform/fatal
helpers (ref: parsec/utils/debug.c, output.c; SURVEY.md §5.5).
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, Optional

_lock = threading.Lock()
_streams: Dict[str, "OutputStream"] = {}
_t0 = time.monotonic()


class OutputStream:
    """A named, verbosity-gated output stream."""

    def __init__(self, name: str, verbosity: int = 0, fh=None) -> None:
        self.name = name
        self.verbosity = verbosity
        self.fh = fh or sys.stderr

    def verbose(self, level: int, msg: str, *args) -> None:
        if level <= self.verbosity:
            if args:
                msg = msg % args
            ts = time.monotonic() - _t0
            with _lock:
                self.fh.write(f"[{ts:10.6f}][{self.name}] {msg}\n")
                self.fh.flush()


def output_stream(name: str, verbosity: Optional[int] = None) -> OutputStream:
    with _lock:
        st = _streams.get(name)
        if st is None:
            env = os.environ.get(f"PARSEC_DEBUG_{name.upper()}")
            default = int(env) if env else _default_verbosity()
            st = OutputStream(name, verbosity=default)
            _streams[name] = st
        if verbosity is not None:
            st.verbosity = verbosity
        return st


def _default_verbosity() -> int:
    try:
        return int(os.environ.get("PARSEC_DEBUG_VERBOSE", "0"))
    except ValueError:
        return 0


#: the default debug stream, analogous to parsec_debug_output
debug = output_stream("debug")
comm_stream = output_stream("comm")
sched_stream = output_stream("sched")
device_stream = output_stream("device")


def set_verbosity(level: int, stream: Optional[str] = None) -> None:
    with _lock:
        if stream is None:
            for st in _streams.values():
                st.verbosity = level
        elif stream in _streams:
            _streams[stream].verbosity = level


def debug_verbose(level: int, stream: OutputStream, msg: str, *args) -> None:
    stream.verbose(level, msg, *args)


def warning(msg: str, *args) -> None:
    if args:
        msg = msg % args
    sys.stderr.write(f"parsec_tpu: WARNING: {msg}\n")


def inform(msg: str, *args) -> None:
    if args:
        msg = msg % args
    sys.stderr.write(f"parsec_tpu: {msg}\n")


class FatalError(RuntimeError):
    pass


def fatal(msg: str, *args) -> None:
    if args:
        msg = msg % args
    raise FatalError(msg)
