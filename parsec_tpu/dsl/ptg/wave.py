"""Wave execution: run a lowered PTG taskpool as batched XLA calls.

The per-task runtime pays one Python/jax dispatch per task (~0.3 ms),
which bounds throughput at small tile sizes no matter how fast the chip
is; whole-DAG capture (capture.py) removes the host loop entirely but
unrolls every instance into one trace, which stops scaling around 10^4
tasks. Wave execution is the TPU-native midpoint, with no direct
reference analog (the reference amortizes dispatch with a ~us C loop,
parsec/scheduling.c:586-625; on TPU the idiomatic fix is batching onto
the MXU, not a faster scalar loop):

- the lowered DAG (lower.py) tracks readiness in dense native counters;
- every collection lives on device as stacked tile pools
  ``[n_tiles, mb, nb]``, one pool per distinct tile shape (ragged
  tilings — the reference's lm%mb edge tiles — split into interior +
  edge + corner pools, each uniform, each batched exactly);
- each ready antichain ("wave") is executed as ONE jitted call (fused
  mode, default): every class/group gathers its input tiles from the
  pre-wave pools, the vmapped bodies run on the MXU, and written tiles
  scatter back in place (donated buffers — no pool copies). Waves whose
  gathers exceed ``wave_fuse_bytes`` fall back to per-(class, chunk)
  calls — they are compute-bound, so per-call dispatch latency is
  already amortized;
- dispatch cost is per *wave* (fused) or per *chunk* (~classes x
  log2(wave size)), never per task, and compiled programs are reused
  across waves and runs.

Semantics notes:
- priorities are ignored: execution is breadth-first by dependence
  level, which is exactly the dataflow order XLA would want anyway;
- a wave may contain a reader of a tile and the (dataflow-independent)
  writer of the same tile (WAR); fused waves gather every input before
  any scatter lands, so same-wave readers see pre-wave values (the
  per-task runtime's copy semantics) even for cyclic WAR; unfused
  waves split readers into an earlier sub-wave instead (cyclic WAR
  raises there);
- supported flows are those whose values live in collection tiles
  (memory-sourced or forwarded from task to task). NEW scratch flows or
  writebacks to a different tile than the flow's slot raise WaveError —
  those run through the per-task runtime instead.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...data.datatype import Datatype
from ...data.reshape import reshape_array
from ...utils import logging as plog
from .ast import Expr
from .lower import LoweredDAG, lower, make_engine
from .runtime import PTGTaskpool, _expand_args, f_prop, scratch_shape

__all__ = ["WaveError", "WaveRunner", "wave"]


class WaveError(RuntimeError):
    pass


def _pick_body(tc_ast):
    for b in tc_ast.bodies:
        if b.device_type not in ("cpu", "recursive"):
            return b
    return tc_ast.bodies[0]


class _ClassPlan:
    """Per-task-class kernel metadata: which flows carry data, where
    their slots live, and the compiled chunked kernels."""

    __slots__ = ("tc", "ast", "flow_idx", "flow_names",
                 "written", "reads", "range_locals", "body_locals", "code",
                 "kernels", "in_tnames", "wb_names", "in_tname", "wb_name",
                 "_kplan")

    def __init__(self, tc) -> None:
        self.tc = tc
        self.ast = tc.ast
        self.flow_idx = [i for i, f in enumerate(tc.ast.flows)
                         if not f.is_ctl]
        self.flow_names = [tc.ast.flows[i].name for i in self.flow_idx]
        from ...data.data import FlowAccess
        self.written = [bool(tc.flows[i].access & FlowAccess.WRITE)
                        for i in self.flow_idx]
        # a flow with in-deps reads its slot's current value (RW reads
        # then writes; WRITE-only flows have no in-deps and may clobber)
        self.reads = [bool(tc.ast.flows[i].deps_in()) for i in self.flow_idx]
        nf = len(self.flow_idx)
        # reshape-property support: per-flow [type]/[type_data] names
        # collected across instances (must be uniform — kernels are
        # per-class), resolved to concrete conversions at kernel trace
        # time when pool tile shapes exist
        self.in_tnames: List[set] = [set() for _ in range(nf)]
        self.wb_names: List[set] = [set() for _ in range(nf)]
        self.in_tname: List[Optional[str]] = [None] * nf
        self.wb_name: List[Optional[str]] = [None] * nf
        self.range_locals = [ld.name for ld in tc.ast.locals
                             if ld.range is not None]
        self.code = compile(_pick_body(tc.ast).code,
                            f"<jdf:{tc.ast.name}:BODY[wave]>", "exec")
        # range locals the body references (co_names: exec reads them as
        # globals): bodies may branch on them in Python (`BETA if k == 0
        # else 1.0`), which a batch tracer cannot do — such locals are
        # made STATIC by sub-chunking the wave on their values
        names = set(self.code.co_names)
        self.body_locals = [i for i, nm in enumerate(self.range_locals)
                            if nm in names]
        self.kernels: Dict[Tuple, Any] = {}
        self._kplan = None

    def kplan(self) -> "_KPlan":
        """The light view kernel traces capture: per-class metadata
        WITHOUT the task-class/taskpool back-references, so kernels
        cached on the (process-cached) LoweredDAG cannot pin runners,
        collections, or device pools for process lifetime."""
        if self._kplan is None:
            self._kplan = _KPlan(self)
        return self._kplan


class _KPlan:
    __slots__ = ("name", "nf", "flow_names", "written", "wb_name",
                 "in_tname", "range_locals", "body_locals", "derived",
                 "code")

    def __init__(self, p: _ClassPlan) -> None:
        self.name = p.ast.name
        self.nf = len(p.flow_idx)
        self.flow_names = p.flow_names
        self.written = p.written
        # in_tname/wb_name lists are assigned ELEMENT-wise by
        # _validate_tnames — sharing the list objects keeps the view
        # current regardless of construction order
        self.wb_name = p.wb_name
        self.in_tname = p.in_tname
        self.range_locals = p.range_locals
        self.body_locals = p.body_locals
        self.derived = [(ld.name, ld.expr) for ld in p.ast.locals
                        if ld.range is None]
        self.code = p.code


# --------------------------------------------------------------------- #
# kernel trace logic: module-level so jitted closures capture only the  #
# light _KPlan views + a collection-pruned env — never a runner (cached #
# traces live on the process-cached LoweredDAG and must not pin pools)  #
# --------------------------------------------------------------------- #
def _resolve_dst_f(genv, p: _KPlan, k, nm, tile_shape, pool_dtype):
    """Concrete Datatype for a validated [type*] name (called at kernel
    TRACE time, when pool tile shapes are in hand)."""
    val = genv.get(nm)
    if isinstance(val, Datatype):
        dst = val
    else:   # validated shorthand
        dst = Datatype(pool_dtype, tuple(tile_shape), nm)
    if tuple(dst.shape) != tuple(tile_shape):
        raise WaveError(
            f"{p.name}.{p.flow_names[k]}: [type={nm}] shape "
            f"{dst.shape} differs from the pool tile {tile_shape}; "
            f"wave pools are fixed-shape — use the per-task runtime")
    return dst


def _make_one_f(genv, p: _KPlan, statics: Tuple, wires: Tuple = ()):
    """Traceable single-instance body with the given static body-local
    values; [type]/[type_data] input conversions (masked casts) applied
    after the gather so XLA fuses them into the body (ref:
    parsec_reshape.c consumer-side promise trigger). ``wires`` carries
    per-flow [type_remote] names for this GROUP (distributed wave:
    instances whose bound producer lives on another rank convert the
    received raw tile consumer-side, the remote_dep_mpi.c:766 lookup)."""
    import jax.numpy as jnp

    flow_names = p.flow_names
    written = p.written
    in_tname = p.in_tname
    range_locals = p.range_locals
    derived = p.derived
    code = p.code
    static_pairs = [(range_locals[i], v)
                    for i, v in zip(p.body_locals, statics)]

    def conv_in(j, v):
        nm = (wires[j] if wires and wires[j] is not None
              else in_tname[j])
        if nm is None:
            return v
        dst = _resolve_dst_f(genv, p, j, nm, tuple(v.shape), v.dtype)
        if dst.compatible_wire(Datatype(v.dtype, tuple(v.shape))):
            return v
        return reshape_array(v, dst)

    def one(loc_row, *flow_vals):
        env = dict(genv)
        for nm, v in zip(range_locals, loc_row):
            env[nm] = v
        for nm, v in static_pairs:  # concrete: bodies may branch
            env[nm] = v
        for nm, ex in derived:
            env[nm] = ex(env)
        for j, (nm, v) in enumerate(zip(flow_names, flow_vals)):
            env[nm] = conv_in(j, v)
        env["np"] = np
        env["jnp"] = jnp
        env["es_rank"] = 0
        env["this_task"] = None
        exec(code, env)
        return tuple(env[nm] for nm, w in zip(flow_names, written) if w)

    return one


def _merge_masked_f(genv, p: _KPlan, j, val, dest_old):
    """Region-masked memory writeback: only in-region elements land;
    the rest keep the DESTINATION's pre-wave values (the detached-clone
    semantics of the per-task runtime). ``val`` is BATCHED [k, ...];
    the declared dtype round-trip mirrors reshape_to + np.copyto, the
    mask broadcasts."""
    import jax.numpy as jnp

    dst = _resolve_dst_f(genv, p, j, p.wb_name[j],
                         tuple(dest_old.shape[1:]), dest_old.dtype)
    conv = val.astype(dst.dtype).astype(dest_old.dtype)
    mask = dst.mask()
    return (conv if mask is None else
            jnp.where(jnp.asarray(mask), conv, dest_old))


def _gather_group_f(kplans, pools, spec, idx_in, idx_out, idx_wbx):
    """Gather one group's inputs + masked-merge destinations from the
    (pre-scatter) pools."""
    _ci, _k, _st, incols, outcols, wbflags, wbxcols, _cnv = spec
    p = kplans[_ci]
    nf = p.nf
    gathered = [pools[incols[j]][idx_in[j]] for j in range(nf)]
    dest_old = {j: pools[outcols[j]][idx_out[j]] for j in range(nf)
                if p.written[j] and p.wb_name[j] is not None
                and wbflags and wbflags[j]}
    wbx_old = {j: pools[wbxcols[j]][idx_wbx[j]] for j in range(nf)
               if wbxcols and wbxcols[j] >= 0}
    return gathered, dest_old, wbx_old


def _compute_scatter_f(genv, kplans, pools, spec, staged, locs, idx_out,
                       idx_wbx) -> None:
    """vmap one group's body over its gathered inputs and scatter
    written outputs into ``pools`` (a list, mutated in place).

    The masked merge applies only at declared MEMORY-target scatters
    (wbflags, per-instance): an instance whose guarded out-dep resolved
    to no target writes in place or renames, and its successors must
    see the FULL body output. A dual-output flow additionally scatters
    the region-merge into its memory target (wbx) while the rename slot
    carries the full value."""
    import jax

    ci, _k, statics, _incols, outcols, _wbflags, wbxcols, cnv = spec
    p = kplans[ci]
    gathered, dest_old, wbx_old = staged
    outs = jax.vmap(_make_one_f(genv, p, statics, cnv))(locs, *gathered)
    oi = 0
    for j, w in enumerate(p.written):
        if not w:
            continue
        cid = outcols[j]
        val = outs[oi]
        if j in dest_old:
            val = _merge_masked_f(genv, p, j, val, dest_old[j])
        pools[cid] = pools[cid].at[idx_out[j]].set(val)
        if j in wbx_old:
            xcid = wbxcols[j]
            pools[xcid] = pools[xcid].at[idx_wbx[j]].set(
                _merge_masked_f(genv, p, j, outs[oi], wbx_old[j]))
        oi += 1


class WaveRunner:
    """Executor for one single-rank PTG taskpool in wave mode."""

    _multirank = False   # DistWaveRunner (wave_dist.py) overrides

    def __init__(self, tp: PTGTaskpool, max_chunk: int = 256) -> None:
        if tp.nb_ranks != 1 and not self._multirank:
            raise WaveError("single-rank wave on a multi-rank taskpool; "
                            "use wave(tp, comm=...) / DistWaveRunner")
        self.tp = tp
        self.max_chunk = max(1, int(max_chunk))
        self.dag: LoweredDAG = lower(tp, allow_multirank=self._multirank)
        from ...collections.collection import DataCollection
        self.collections: Dict[str, Any] = {
            name: c for name, c in tp.global_env.items()
            if isinstance(c, DataCollection)}
        if not self.collections:
            raise WaveError("taskpool binds no data collections")
        self.coll_names = sorted(self.collections)
        # Pools are SHAPE-SPLIT: each collection's tiles are partitioned
        # by their true tile shape and every shape class becomes its own
        # stacked pool. A ragged tiling (the reference's first-class
        # lm%mb edge tiles, parsec/data_dist/matrix/matrix.c:106,116)
        # yields at most 4 pools per matrix (interior + bottom/right
        # edge + corner); bodies see exact shapes, so edge tiles need no
        # padding or masking and the math is the per-task runtime's.
        # Chunk kernels already group by the per-instance pool
        # signature, so mixed-shape classes batch per shape. Pool order
        # is deterministic (largest tile first within each collection)
        # and derived from the distribution only — SPMD ranks agree.
        self.pool_names: List[str] = []       # pool id -> collection name
        self._pool_coords: List[List[Tuple]] = []
        self._pool_shapes: List[Tuple] = []
        self._pool_of: Dict[str, Dict[Tuple, Tuple[int, int]]] = {}
        for n in self.coll_names:
            coll = self.collections[n]
            coords = sorted(coll.tiles())
            ts = getattr(coll, "tile_shape", None)
            if callable(ts):
                by_shape: Dict[Tuple, List[Tuple]] = {}
                for c in coords:
                    by_shape.setdefault(
                        tuple(int(v) for v in ts(*c)), []).append(c)
                shapes = sorted(by_shape,
                                key=lambda s: (-int(np.prod(s)), s))
            else:
                # no descriptor contract: one pool, shapes resolved at
                # staging (np.stack still rejects a ragged tiling there
                # — ragged needs tile_shape; no payload is touched here,
                # unused collections stay unstaged)
                by_shape = {None: coords}
                shapes = [None]
            loc = self._pool_of.setdefault(n, {})
            for sh in shapes:
                pid = len(self.pool_names)
                self.pool_names.append(n)
                self._pool_coords.append(by_shape[sh])
                self._pool_shapes.append(sh)
                for i, c in enumerate(by_shape[sh]):
                    loc[c] = (pid, i)
        self.plans = [_ClassPlan(tc) for tc in tp.task_classes]
        # reshape properties ([type]/[type_data]) are served IN-KERNEL:
        # input conversions apply after the gather (masked cast, XLA
        # fuses them into the body), region-masked memory writebacks
        # merge with the pre-body tile value at scatter. The names must
        # be uniform per (class, flow) — kernels are per-class — and
        # conversions materialize at first execute when pool tile
        # shapes are known. type_remote is wire-format only and is
        # ignored here (single-rank: local edges never reshape on it;
        # DistWaveRunner applies it per instance on cross-rank edges
        # via the _wire_tname_of hook).
        # NEW scratch flows get per-class scratch pools (ids after the
        # real collections), zero-initialized each run like the
        # per-task runtime's runtime-allocated NEW tiles.
        self._n_real_colls = len(self.pool_names)
        # wave-level call fusion (one XLA call per wave): MCA-tunable,
        # with a gather-bytes budget above which big (compute-bound)
        # waves keep per-chunk calls
        from ...utils.params import params
        self._fuse = bool(params.get_or(
            "wave_fuse", "bool", True))
        self._fuse_bytes = int(params.get_or(
            "wave_fuse_bytes", "int", 1 << 30))
        self._fuse_programs = int(params.get_or(
            "wave_fuse_programs", "int", 128))
        self._fused_kerns: Dict[Tuple, Any] = {}
        self._scratch: Dict[Tuple, Dict[str, Any]] = {}
        self._g2l = None   # DistWaveRunner: global->local pool row maps
        # slot tables: per task, per (non-ctl) flow position in the
        # class's flow_idx list -> flat tile index (collection fixed per
        # class/flow, validated during assignment)
        self._assign_slots()
        self._validate_tnames()
        self._kplans = [p.kplan() for p in self.plans]
        self._trace_env = self._build_trace_env()

    # ------------------------------------------------------------------ #
    # slot assignment                                                    #
    # ------------------------------------------------------------------ #
    def _wire_tname_of(self, tc, f, env) -> Optional[str]:
        """[type_remote] hook: wire conversions exist only on cross-
        rank edges — the distributed runner overrides this; single-rank
        wave has no remote edges."""
        return None

    def _assign_slots(self) -> None:
        dag = self.dag
        n = dag.n_tasks
        # per-INSTANCE wire conversions ([type_remote] on a bound
        # remote edge, dist only): sparse (task, flow) -> name; chunks
        # group by the per-flow name tuple so per-class kernels stay
        # uniform while local and remote instances convert differently
        self._wconv: Dict[Tuple[int, int], str] = {}
        max_df = max((len(p.flow_idx) for p in self.plans), default=0)
        slot = np.full((n, max_df), -1, np.int32)
        # topo order via Kahn over the lowered CSR
        indeg = dag.indegree.copy()
        head = 0
        order = [int(t) for t in np.nonzero(indeg == 0)[0]]
        while head < len(order):
            t = order[head]
            head += 1
            for e in range(int(dag.indptr[t]), int(dag.indptr[t + 1])):
                s = int(dag.succ[e])
                indeg[s] -= 1
                if indeg[s] == 0:
                    order.append(s)
        if len(order) != n:
            raise WaveError("cycle in lowered DAG")

        flow_pos = []  # per class: ast flow index -> dense position
        for p in self.plans:
            pos = {fi: k for k, fi in enumerate(p.flow_idx)}
            flow_pos.append(pos)

        # class-local ordinal of each task (scratch-pool slot index for
        # NEW flows: one scratch tile per instance)
        ordinal = np.zeros(n, np.int32)
        counts: Dict[int, int] = {}
        for t in range(n):
            ci = int(dag.class_of[t])
            ordinal[t] = counts.get(ci, 0)
            counts[ci] = counts.get(ci, 0) + 1
        self._class_ordinal = ordinal
        self._class_count = counts

        # IN and OUT slots are SEPARATE: a written flow without a memory
        # out-dep renames into a per-instance scratch slot, so its body
        # output reaches successors without mutating the home tile —
        # the per-task runtime's copy-rename semantics (this also lets
        # instances write back to a DIFFERENT tile than they read, and
        # lets guarded deps bind different collections per instance:
        # chunks group by the per-task collection signature)
        slot_out = np.full((n, max_df), -1, np.int32)
        scoll = np.full((n, max_df), -1, np.int16)
        socoll = np.full((n, max_df), -1, np.int16)
        # per-INSTANCE: does this flow write a declared memory target
        # (the only scatters where a [type*] writeback mask applies)?
        wb_apply = np.zeros((n, max_df), bool)
        # per-INSTANCE extra masked scatter: a flow with BOTH a masked
        # memory writeback AND task successors produces TWO values —
        # successors get the full body output (rename slot), memory
        # gets the region-merge; these arrays carry the memory target
        wbx_cid = np.full((n, max_df), -1, np.int16)
        wbx_idx = np.full((n, max_df), -1, np.int32)
        self._wbx_cid, self._wbx_idx = wbx_cid, wbx_idx

        for t in order:
            ci = int(dag.class_of[t])
            p = self.plans[ci]
            tc = p.tc
            env = tc.env_of(dag.locals_of[t])
            for k, fi in enumerate(p.flow_idx):
                f = tc.ast.flows[fi]
                s = self._slot_of_flow(t, f, env, flow_pos, slot, scoll,
                                       slot_out, socoll)
                if s is None:
                    raise WaveError(
                        f"{p.ast.name}{dag.locals_of[t]}.{f.name}: flow "
                        f"does not resolve to a collection tile or scratch "
                        f"pool (NULL flows need the per-task runtime)")
                coll_id, idx = s
                scoll[t, k] = coll_id
                slot[t, k] = idx
                tname = self._inst_in_tname(f, env)
                p.in_tnames[k].add(tname)
                wnm = self._wire_tname_of(tc, f, env)
                if wnm is not None:
                    self._wconv[(t, k)] = wnm
                if p.written[k]:
                    out_cid, out_idx, has_target = self._out_slot_of_flow(
                        t, p, k, f, env, coll_id, idx, tname,
                        wbx_cid, wbx_idx)
                    socoll[t, k] = out_cid
                    slot_out[t, k] = out_idx
                    wb_apply[t, k] = has_target
        self._slot = slot
        self._slot_out = slot_out
        self._slot_coll = scoll
        self._slot_out_coll = socoll
        self._wb_apply = wb_apply
        # only collections the DAG actually touches are staged; only
        # written ones are scattered back (D2H can be ~4 MB/s — a full
        # gather of an untouched pool costs minutes)
        self._used_colls = ({int(c) for c in np.unique(scoll) if c >= 0}
                            | {int(c) for c in np.unique(socoll) if c >= 0}
                            | {int(c) for c in np.unique(wbx_cid) if c >= 0})
        self._written_colls = (
            {int(c) for c in np.unique(socoll) if c >= 0}
            | {int(c) for c in np.unique(wbx_cid) if c >= 0})

    def _inst_in_tname(self, f, env) -> Optional[str]:
        """The [type*] name this instance's input edge declares (same
        first-applicable-dep rule as the runtime's _input_dtt;
        type_remote is wire-only and never applies locally)."""
        for d in f.deps_in():
            t = d.resolve(env)
            if t is None:
                continue
            props = d.properties
            if t.kind == "memory":
                nm = props.get("type_data") or props.get("type")
            else:
                nm = props.get("type")
            return None if nm == "full" else nm
        return None

    def _scratch_slot(self, tid, f, env, shape=None) -> Tuple[int, int]:
        """NEW flow: one scratch tile per instance in a per-(class,
        flow) zero-initialized pool (the runtime-allocated NEW tile
        analog; shape from [shape=]/[dtype=] props, uniform across
        instances — pools are stacked arrays)."""
        ci = int(self.dag.class_of[tid])
        if shape is None:
            shape = scratch_shape(f, env)
        if shape is None:
            raise WaveError(
                f"{self.plans[ci].ast.name}.{f.name}: NEW flow needs a "
                f"[shape=...] property")
        key = (ci, f.name, "new")
        sp = self._scratch.get(key)
        if sp is None:
            sp = self._scratch[key] = {
                "cid": self._n_real_colls + len(self._scratch),
                "shape": shape,
                "dtype": np.dtype(f_prop(f, "dtype", "float32")),
                "like": None,
                "n": self._class_count[ci],
                "label": f"{self.plans[ci].ast.name}.{f.name}",
            }
        elif sp["shape"] != shape:
            raise WaveError(
                f"{sp['label']}: NEW shapes differ across instances "
                f"({sp['shape']} vs {shape}); scratch pools are stacked")
        return sp["cid"], int(self._class_ordinal[tid])

    def _rename_slot(self, tid, f, like_cid: int) -> Tuple[int, int]:
        """Written flow with NO memory out-target: its output must reach
        successors without touching the home tile — rename into a
        per-instance scratch slot (the copy-rename the per-task runtime
        gets from fresh DataCopies). Tile shape/dtype copied from the
        input slot's pool at staging."""
        ci = int(self.dag.class_of[tid])
        # keyed by the like-pool: instances binding different input
        # pools (guarded collections, or shape-split edge tiles) rename
        # into separate pools so tile shapes stay exact per pool. Rows
        # are per-key ordinals (assignment order is the deterministic
        # topo walk, so SPMD ranks agree), sized to the instances that
        # actually rename through this pool — not the whole class.
        key = (ci, f.name, "ren", like_cid)
        sp = self._scratch.get(key)
        if sp is None:
            sp = self._scratch[key] = {
                "cid": self._n_real_colls + len(self._scratch),
                "shape": None,
                "dtype": None,
                "like": like_cid,
                "rows": {},
                "n": 0,
                "label": f"{self.plans[ci].ast.name}.{f.name}",
            }
        row = sp["rows"].setdefault(int(tid), len(sp["rows"]))
        sp["n"] = len(sp["rows"])
        return sp["cid"], row

    def _out_slot_of_flow(self, tid, p, k, f, env, in_cid, in_idx, tname,
                          wbx_cid, wbx_idx) -> Tuple[int, int, bool]:
        """Where this written flow's output lands.

        Mirrors the runtime's copy binding: a flow's body mutates the
        copy BOUND to it, so by default the output lands in the input
        slot (home tiles and shared producer copies are mutated in
        place, like the reference's parsec_data_copy_t sharing). The
        exceptions:
        - a memory out-dep names the tile — must be the input slot
          (or the input is private scratch: NEW tiles write back home);
        - a [type*] INPUT conversion applies — the runtime binds a
          DETACHED converted copy there, so the output renames into a
          private scratch slot and the home/producer value stays put.
        """
        targets = set()
        inst_masked = False
        has_task_succ = False
        for d in f.deps_out():
            t = d.resolve(env)
            if t is None:
                continue
            if t.kind == "task":
                has_task_succ = True
                continue
            if t.kind != "memory":
                continue
            coords = tuple(int(a(env)) for a in t.args)
            hit = self._locate_tile(t.collection, coords)
            if hit is None:
                raise WaveError(
                    f"{p.ast.name}.{f.name}: writes back to unbound "
                    f"collection {t.collection!r}")
            targets.add(hit)
            nm = d.properties.get("type_data") or d.properties.get("type")
            nm = None if nm == "full" else nm
            inst_masked = inst_masked or nm is not None
            p.wb_names[k].add(nm)
        if len(targets) > 1:
            raise WaveError(
                f"{p.ast.name}.{f.name}: one instance writes back to "
                f"multiple tiles {sorted(targets)}; unsupported in wave "
                f"mode")
        if targets:
            cid, idx = next(iter(targets))
            if inst_masked and has_task_succ:
                # TWO distinct values leave this flow: successors get
                # the FULL body output (runtime: the detached clone),
                # memory gets the region-masked merge. Main scatter
                # renames; the memory target rides the extra-scatter
                # arrays (masked merge against its own old value).
                wbx_cid[tid, k] = cid
                wbx_idx[tid, k] = idx
                return self._rename_slot(tid, f, in_cid) + (False,)
            if (cid, idx) != (in_cid, in_idx) and \
                    in_cid < self._n_real_colls:
                raise WaveError(
                    f"{p.ast.name}.{f.name}: writes back to a different "
                    f"tile than its slot; unsupported in wave mode (the "
                    f"body would also mutate the source in the runtime)")
            return cid, idx, True
        if tname is not None:
            return self._rename_slot(tid, f, in_cid) + (False,)
        return in_cid, in_idx, False

    def _slot_of_flow(self, tid, f, env, flow_pos, slot, scoll,
                      slot_out, socoll):
        deps_in = f.deps_in()
        for d in deps_in:
            t = d.resolve(env)
            if t is None:
                continue
            if t.kind == "memory":
                coords = tuple(int(a(env)) for a in t.args)
                return self._locate_tile(t.collection, coords)
            if t.kind == "new":
                return self._scratch_slot(tid, f, env)
            if t.kind == "task":
                for args in _expand_args(t.args, env):
                    past = self.tp.jdf.task_class_by_name(t.task_class)
                    pkey = (t.task_class, past.locals_from_param_args(args))
                    pid = self.dag.id_of.get(pkey)
                    if pid is None:
                        continue  # out-of-space producer: inapplicable
                    pci = int(self.dag.class_of[pid])
                    pplan = self.plans[pci]
                    pfi = next(i for i, pf in enumerate(pplan.ast.flows)
                               if pf.name == t.flow)
                    k = flow_pos[pci].get(pfi)
                    if k is None:
                        return None
                    # a WRITTEN producer flow hands successors its OUT
                    # slot (post-rename); a READ flow forwards its input
                    if pplan.written[k]:
                        idx = int(slot_out[pid, k])
                        cid = int(socoll[pid, k])
                    else:
                        idx = int(slot[pid, k])
                        cid = int(scoll[pid, k])
                    if idx < 0:
                        return None
                    return cid, idx
                continue
            return None  # null
        if not deps_in:
            # WRITE-only flow: bind to its memory out-target, or a
            # scratch pool when it only feeds successors ([shape=] set)
            for d in f.deps_out():
                t = d.resolve(env)
                if t is not None and t.kind == "memory":
                    coords = tuple(int(a(env)) for a in t.args)
                    return self._locate_tile(t.collection, coords)
            ssh = scratch_shape(f, env)
            if ssh is not None:
                return self._scratch_slot(tid, f, env, shape=ssh)
        return None

    def _locate_tile(self, coll_name: str,
                     coords: Tuple[int, ...]) -> Optional[Tuple[int, int]]:
        """Map a dep target to its (pool id, pool row); None when the
        collection is unbound. Vector-style 1-arg targets pad a trailing
        0 (data_of(m) == data_of(m, 0))."""
        loc = self._pool_of.get(coll_name)
        if loc is None:
            return None
        hit = loc.get(coords)
        while hit is None and len(coords) < 2:
            coords = coords + (0,)
            hit = loc.get(coords)
        if hit is None:
            raise WaveError(f"no tile {coords} in collection "
                            f"{coll_name}")
        return hit

    # ------------------------------------------------------------------ #
    # reshape-property conversions                                       #
    # ------------------------------------------------------------------ #
    def _validate_tnames(self) -> None:
        """Uniformity + resolvability of collected [type*] names (the
        kernels are per-class, so per-instance variation is unservable;
        the general runtime handles those JDFs)."""
        for p in self.plans:
            for k in range(len(p.flow_idx)):
                for which, names in (("in", p.in_tnames[k]),
                                     ("writeback", p.wb_names[k])):
                    real = {n for n in names if n is not None}
                    if len(real) > 1 or (real and None in names):
                        raise WaveError(
                            f"{p.ast.name}.{p.flow_names[k]}: [type*] "
                            f"names vary across instances "
                            f"({sorted(names, key=str)}); per-class wave "
                            f"kernels need one — use the per-task runtime")
                    for nm in real:
                        val = self.tp.global_env.get(nm)
                        if not isinstance(val, Datatype) and \
                                nm not in ("lower", "upper", "full"):
                            raise WaveError(
                                f"{p.ast.name}.{p.flow_names[k]} "
                                f"({which}): [type={nm}] is neither a "
                                f"Datatype global nor a region shorthand")
                p.in_tname[k] = next(iter(
                    {n for n in p.in_tnames[k] if n is not None}), None)
                p.wb_name[k] = next(iter(
                    {n for n in p.wb_names[k] if n is not None}), None)

    def _build_trace_env(self) -> Dict[str, Any]:
        """global_env for kernel TRACING, with DataCollection values
        dropped unless a body or derived-local expression names them:
        cached kernel traces (they live on the process-cached DAG) must
        not pin collections — and through their attached lazy device
        copies, result pools — for process lifetime."""
        from ...collections.collection import DataCollection
        needed = set()
        for p in self.plans:
            needed |= set(p.code.co_names)
            for ld in p.ast.locals:
                if ld.range is None:
                    needed |= set(ld.expr._code.co_names)
            if p.ast.priority is not None:
                needed |= set(p.ast.priority._code.co_names)
        env = {k: v for k, v in self.tp.global_env.items()
               if not isinstance(v, DataCollection) or k in needed}
        # a body that NAMES a collection bakes that instance into the
        # trace: such kernels must stay per-runner (a later taskpool
        # with the same structural signature but different data would
        # reuse the stale baked values) — and per-runner caching also
        # avoids pinning the named collection process-long
        self._kernels_shareable = not any(
            isinstance(env.get(nm), DataCollection) for nm in needed)
        return env

    # ------------------------------------------------------------------ #
    # kernels (trace logic lives in the module-level _*_f functions so   #
    # cached traces capture kplans + a pruned env, never the runner)     #
    # ------------------------------------------------------------------ #
    def _kernel(self, ci: int, k: int, statics: Tuple, incols: Tuple,
                outcols: Tuple, wbflags: Tuple = (), wbxcols: Tuple = (),
                cnv: Tuple = ()):
        """The jitted chunk kernel for class ``ci``, chunk size ``k``,
        static body-local values ``statics``, per-flow pool ids
        ``incols``/``outcols``, per-flow writeback-mask applicability
        ``wbflags``, and per-flow extra masked-scatter pool ids
        ``wbxcols`` (guarded deps may bind different pools / have or
        lack a memory target per instance — chunks group by the full
        signature): fn(pools, locals_i32[k, n_locals], idx_in, idx_out,
        idx_wbx [n_flows, k]) -> pools with written slots scattered.

        Kernel traces capture ONLY light per-class metadata (kplans)
        and a collection-pruned trace env — never the runner — so the
        DAG-level cache cannot pin pools or collections (see
        _build_trace_env)."""
        p = self.plans[ci]
        key = (k, statics, incols, outcols, wbflags, wbxcols, cnv)
        kern = p.kernels.get(key)
        if kern is not None:
            return kern
        spec = (ci, k, statics, incols, outcols, wbflags, wbxcols, cnv)
        if self._kernels_shareable:
            kern = self.dag.kernel_cache.get(spec)
            if kern is not None:
                p.kernels[key] = kern
                return kern
        import jax

        kplans = self._kplans
        genv = self._trace_env

        def chunk_fn(pools, locs, idx_in, idx_out, idx_wbx):
            staged = _gather_group_f(kplans, pools, spec, idx_in,
                                     idx_out, idx_wbx)
            pools = list(pools)
            _compute_scatter_f(genv, kplans, pools, spec, staged, locs,
                               idx_out, idx_wbx)
            return tuple(pools)

        kern = jax.jit(chunk_fn, donate_argnums=(0,))
        p.kernels[key] = kern
        if self._kernels_shareable:
            self.dag.kernel_cache[spec] = kern
        return kern

    def _fused_kernel(self, specs: Tuple):
        """ONE jitted call for a whole wave (all classes, all groups):
        every group gathers from the PRE-WAVE pools first, then all
        bodies run and all scatters land. Because a wave is an
        antichain, no group's input depends on another's output, and
        gather-before-any-scatter gives every same-wave reader the
        pre-wave value — WAR semantics without sub-wave layering (and
        without its extra dispatches). Dispatch cost becomes one call
        per wave, the robustness answer to per-call link latency at
        small NB (VERDICT r3 weak #2)."""
        kern = self._fused_kerns.get(specs)
        if kern is not None:
            return kern
        if self._kernels_shareable:
            kern = self.dag.kernel_cache.get(("fused", specs))
            if kern is not None:
                self._fused_kerns[specs] = kern
                return kern
        import jax

        kplans = self._kplans
        genv = self._trace_env

        def wave_fn(pools, args):
            staged = [_gather_group_f(kplans, pools, sp, a["idx_in"],
                                      a["idx_out"], a["idx_wbx"])
                      for sp, a in zip(specs, args)]
            plist = list(pools)
            for sp, a, st in zip(specs, args, staged):
                _compute_scatter_f(genv, kplans, plist, sp, st,
                                   a["locs"], a["idx_out"], a["idx_wbx"])
            return tuple(plist)

        kern = jax.jit(wave_fn, donate_argnums=(0,))
        self._fused_kerns[specs] = kern
        if self._kernels_shareable:
            self.dag.kernel_cache[("fused", specs)] = kern
        return kern

    @staticmethod
    def _chunks(k: int, max_chunk: int) -> List[int]:
        """Binary decomposition of k bounded by max_chunk: exact sizes
        from a fixed set, so compiled programs are reused."""
        out = []
        while k >= max_chunk:
            out.append(max_chunk)
            k -= max_chunk
        b = 1
        while k:
            if k & 1:
                out.append(b)
            k >>= 1
            b <<= 1
        return out

    # ------------------------------------------------------------------ #
    # execution                                                          #
    # ------------------------------------------------------------------ #
    def _frontier_entries(self, ids: np.ndarray, classes: np.ndarray,
                          pools: Tuple):
        """Break a frontier (or sub-wave) into chunk-call entries
        [(spec, arrays)] and estimate their total gather bytes.

        (No priority ordering: a wave is an antichain and every member
        executes before the next readiness update — order has no
        observable effect.) Body-referenced locals become static kernel
        args, and guarded deps may bind different pools per instance:
        members group by (locals statics, pool signature)."""
        dag = self.dag
        entries = []
        total = 0
        for ci in np.unique(classes):
            members = ids[classes == ci]
            p = self.plans[int(ci)]
            nf = len(p.flow_idx)
            groups: Dict[Tuple, List[int]] = {}
            none_cnv = (None,) * nf
            for t in members:
                sv = tuple(int(dag.locals_of[t][i])
                           for i in p.body_locals)
                icl = tuple(int(c) for c in self._slot_coll[t, :nf])
                ocl = tuple(int(c) for c in self._slot_out_coll[t, :nf])
                wfl = tuple(bool(b) for b in self._wb_apply[t, :nf])
                xcl = tuple(int(c) for c in self._wbx_cid[t, :nf])
                cnv = (tuple(self._wconv.get((int(t), j))
                             for j in range(nf))
                       if self._wconv else none_cnv)
                groups.setdefault((sv, icl, ocl, wfl, xcl, cnv),
                                  []).append(int(t))
            for (statics, icl, ocl, wfl, xcl, cnv), g in groups.items():
                garr = np.asarray(g, np.int64)
                off = 0
                for k in self._chunks(len(garr), self.max_chunk):
                    chunk = garr[off:off + k]
                    off += k
                    lrows = [dag.locals_of[t] for t in chunk]
                    nl = len(lrows[0])
                    locs = (np.asarray(lrows, np.int32).reshape(k, nl)
                            if nl else np.zeros((k, 0), np.int32))
                    idx_in = self._slot[chunk, :nf].T.copy()
                    idx_out = self._slot_out[chunk, :nf].T.copy()
                    idx_wbx = self._wbx_idx[chunk, :nf].T.copy()
                    if self._g2l is not None:
                        # sliced pools (dist): translate the global
                        # tile indices into this rank's pool rows
                        bad = False
                        for j in range(nf):
                            idx_in[j] = self._g2l[icl[j]][idx_in[j]]
                            bad |= bool((idx_in[j] < 0).any())
                            if ocl[j] >= 0:
                                idx_out[j] = self._g2l[ocl[j]][idx_out[j]]
                                bad |= bool((idx_out[j] < 0).any())
                            if xcl[j] >= 0:
                                idx_wbx[j] = self._g2l[xcl[j]][idx_wbx[j]]
                                bad |= bool((idx_wbx[j] < 0).any())
                        if bad:
                            raise WaveError(
                                "sliced-pool translation hit a tile "
                                "this rank never staged (local-map "
                                "construction bug)")
                    spec = (int(ci), k, statics, icl, ocl, wfl, xcl, cnv)
                    entries.append((spec, {"locs": locs, "idx_in": idx_in,
                                           "idx_out": idx_out,
                                           "idx_wbx": idx_wbx}))
                    for j in range(nf):
                        pl = pools[icl[j]]
                        total += k * int(np.prod(pl.shape[1:])) * \
                            np.dtype(pl.dtype).itemsize
        return entries, total

    @staticmethod
    def _trace_error(exc: Exception, label: str):
        if "Tracer" in type(exc).__name__ or \
                "Concretization" in type(exc).__name__:
            return WaveError(
                f"{label}: body cannot be batch-traced (it branches on "
                f"a derived local or data value in Python); run this "
                f"taskpool through the per-task runtime")
        return None

    def _write_keys(self, t: int, p, k: int) -> List[Tuple[int, int]]:
        """The (pool, row) slots a task's written flow scatters into
        (out slot, plus the dual-output masked memory target)."""
        wkeys = [(int(self._slot_out_coll[t, k]),
                  int(self._slot_out[t, k]))]
        if int(self._wbx_cid[t, k]) >= 0:
            wkeys.append((int(self._wbx_cid[t, k]),
                          int(self._wbx_idx[t, k])))
        return wkeys

    def _check_two_writers(self, ids: np.ndarray,
                           classes: np.ndarray) -> None:
        """Two same-wave writers of one tile race regardless of call
        structure (the last scatter would win arbitrarily)."""
        writes: Dict[Tuple[int, int], int] = {}
        for pos, t in enumerate(ids):
            p = self.plans[int(classes[pos])]
            for k in range(len(p.flow_idx)):
                if not p.written[k]:
                    continue
                for key in self._write_keys(int(t), p, k):
                    prev = writes.get(key)
                    if prev is not None and prev != int(t):
                        raise WaveError(
                            f"frontier holds two writers of the same "
                            f"tile (tasks {prev} and {int(t)}): the "
                            f"DAG races — in-place scatters would "
                            f"keep an arbitrary one")
                    writes[key] = int(t)

    def _call_chunk(self, spec: Tuple, a: Dict, pools: Tuple) -> Tuple:
        try:
            return self._kernel(*spec)(
                pools, a["locs"], a["idx_in"], a["idx_out"], a["idx_wbx"])
        except Exception as exc:
            werr = self._trace_error(exc, self.plans[spec[0]].ast.name)
            if werr is not None:
                raise werr from exc
            raise

    def _execute_frontier(self, ids: np.ndarray, classes: np.ndarray,
                          pools: Tuple) -> Tuple[Tuple, int]:
        """Execute one ready antichain (or the local slice of one).

        Fused mode (default): the whole wave is ONE jitted call —
        every group gathers from the pre-wave pools before any scatter
        lands, which both amortizes per-call dispatch latency (the NB
        exposure of one-call-per-(class, chunk)) and gives WAR/cyclic-
        WAR frontiers their copy semantics for free (a single-entry
        wave gets the same semantics from its chunk kernel directly —
        it, too, gathers before scattering). Fallbacks keep per-chunk
        calls with WAR sub-wave layering: waves whose gathers exceed
        ``wave_fuse_bytes`` (compute-bound — dispatch latency is
        amortized by the work itself) and waves beyond the
        ``wave_fuse_programs`` compile budget (fused programs are
        cached per wave SIGNATURE; DAGs with endlessly varying wave
        shapes must not compile without bound)."""
        entries = None
        if self._fuse:
            entries, gather_bytes = self._frontier_entries(
                ids, classes, pools)
            if gather_bytes <= self._fuse_bytes:
                if len(entries) == 1:
                    self._check_two_writers(ids, classes)
                    return self._call_chunk(entries[0][0], entries[0][1],
                                            pools), 1
                specs = tuple(e[0] for e in entries)
                if specs in self._fused_kerns or \
                        len(self._fused_kerns) < self._fuse_programs:
                    self._check_two_writers(ids, classes)
                    return self._call_fused(specs, entries, pools), 1
        n_calls = 0
        try:
            layers = self._split_war(ids, classes)
        except WaveError:
            if entries is None:
                raise       # fusion off: the layered contract stands
            # _split_war re-raises two-writer races via
            # _check_two_writers; if that passes, the failure was a
            # CYCLIC WAR frontier — only the fused gather-before-
            # scatter form can serve it, so correctness overrides the
            # fusion byte/program budgets
            self._check_two_writers(ids, classes)
            return self._call_fused(tuple(e[0] for e in entries),
                                    entries, pools), 1
        for sids, cls in layers:
            if len(layers) == 1 and entries is not None:
                sub_entries = entries
            else:
                sub_entries, _ = self._frontier_entries(sids, cls, pools)
            for spec, a in sub_entries:
                pools = self._call_chunk(spec, a, pools)
                n_calls += 1
        return pools, n_calls

    def _call_fused(self, specs: Tuple, entries, pools: Tuple) -> Tuple:
        args = [e[1] for e in entries]
        try:
            return self._fused_kernel(specs)(pools, args)
        except Exception as exc:
            werr = self._trace_error(exc, "fused wave")
            if werr is not None:
                raise werr from exc
            raise

    def execute(self, pools: Tuple) -> Tuple:
        """Run the DAG over device tile pools (stacked arrays ordered
        by self.pool_names, shape-split per collection); returns final
        pools."""
        import time as _time

        dag = self.dag
        eng = make_engine(dag)
        ready = np.asarray(eng.start(), np.int32)
        n_waves = n_calls = 0
        t0 = _time.perf_counter()
        while ready.size:
            n_waves += 1
            pools, nc = self._execute_frontier(ready, dag.class_of[ready],
                                               pools)
            n_calls += nc
            ready = np.asarray(eng.complete_batch(ready), np.int32)
        done = eng.completed() if hasattr(eng, "completed") else dag.n_tasks
        if int(done) != dag.n_tasks:
            raise WaveError(
                f"wave execution stalled: {done}/{dag.n_tasks} tasks ran")
        # observability: the engineering counters a profiler of the
        # per-task path would have shown (wave bypasses PINS sites by
        # design — dispatch IS what it amortizes away)
        self.stats = {"tasks": dag.n_tasks, "waves": n_waves,
                      "kernel_calls": n_calls,
                      "dispatch_secs": round(_time.perf_counter() - t0, 6),
                      "compiled_kernels": sum(len(p.kernels)
                                              for p in self.plans)
                      + len(self._fused_kerns)}
        plog.debug.verbose(3, "wave %s: %s", self.tp.name, self.stats)
        return pools

    def _split_war(self, ids: np.ndarray, classes: np.ndarray):
        """Split a frontier so no in-place scatter clobbers a same-wave
        read. Anti-dependence edges (reader R of a tile that a different
        frontier task W writes: R must run before W) are layered with
        Kahn's algorithm; each layer is anti-dep-free and executes as one
        batched sub-wave. A cyclic frontier (two tasks each reading the
        tile the other writes — legal dataflow, but unservable by
        in-place scatters) raises WaveError: run it through the per-task
        runtime, whose copies rename WAR hazards away."""
        self._check_two_writers(ids, classes)
        reads: Dict[Tuple[int, int], List[int]] = {}
        writes: Dict[Tuple[int, int], int] = {}
        for pos, t in enumerate(ids):
            p = self.plans[int(classes[pos])]
            for k in range(len(p.flow_idx)):
                # IN and OUT slots differ for renamed/cross-tile writes:
                # the read is against the in slot, the write against the
                # out slot (an RW flow is both)
                if p.reads[k] or not p.written[k]:
                    key = (int(self._slot_coll[t, k]), int(self._slot[t, k]))
                    reads.setdefault(key, []).append(int(t))
                if p.written[k]:
                    for key in self._write_keys(int(t), p, k):
                        writes[key] = int(t)
        out_edges: Dict[int, List[int]] = {}
        indeg: Dict[int, int] = {int(t): 0 for t in ids}
        n_conf = 0
        for key, ts in reads.items():
            w = writes.get(key)
            if w is None:
                continue
            for r in ts:
                if r == w:
                    continue
                out_edges.setdefault(r, []).append(w)
                indeg[w] += 1
                n_conf += 1
        if n_conf == 0:
            return [(ids, classes)]
        cls_of = {int(t): int(c) for t, c in zip(ids, classes)}
        layer = [t for t in indeg if indeg[t] == 0]
        done = 0
        layers = []
        while layer:
            layers.append(layer)
            done += len(layer)
            nxt: List[int] = []
            for t in layer:
                for w in out_edges.get(t, ()):
                    indeg[w] -= 1
                    if indeg[w] == 0:
                        nxt.append(w)
            layer = nxt
        if done != len(ids):
            raise WaveError(
                "frontier has cyclic write-after-read conflicts; this DAG "
                "needs the per-task runtime (copies rename WAR hazards)")
        return [(np.asarray(ls, np.int64),
                 np.asarray([cls_of[t] for t in ls], np.int32))
                for ls in layers]

    # ------------------------------------------------------------------ #
    # convenience: run against the bound collections                     #
    # ------------------------------------------------------------------ #
    def build_pools(self, device=None, sharding=None) -> Tuple:
        """Stage each collection as stacked [n_tiles, mb, nb] device
        arrays, one per shape-split pool (self.pool_names order).
        ``sharding`` (a jax.sharding.Sharding over the tile dims,
        e.g. NamedSharding(mesh, P(None, "tp", "sp"))) runs every wave
        kernel SPMD over the mesh — GSPMD partitions the batched tile
        ops and inserts the collectives (the scaling-book recipe); right
        for large NB where one tile's FLOPs span several chips."""
        import jax
        import jax.numpy as jnp
        pools = []
        for pid, name in enumerate(self.pool_names):
            if pid not in self._used_colls:
                pools.append(jnp.zeros((0,), np.float32))  # placeholder
                continue
            coll = self.collections[name]
            tiles = []
            for c in self._pool_coords[pid]:
                data = coll.data_of(*c)
                tiles.append(np.asarray(data.sync_to_host().payload))
            stacked = np.stack(tiles)
            if sharding is not None:
                arr = self._put_sharded(stacked, sharding)
            elif device is not None:
                arr = jax.device_put(stacked, device)
            else:
                arr = jnp.asarray(stacked)
            pools.append(arr)
        # scratch pools (NEW flows + write renames): zero-initialized
        # each run, ids after real collections; rename pools copy tile
        # shape/dtype from the pool they rename ("like" — already
        # staged: its cid is always smaller). A tile-pool sharding spec
        # needn't fit scratch shapes — scratch replicates on the mesh
        # (or stays single-device without one).
        for cnt, shape, dt in self._scratch_specs(pools):
            z = np.zeros((cnt,) + shape, dt)
            if sharding is not None:
                pools.append(self._put_replicated(z, sharding))
            else:
                pools.append(jax.device_put(z, device)
                             if device is not None else jnp.asarray(z))
        return tuple(pools)

    def _scratch_specs(self, pools) -> List[Tuple[int, Tuple, Any]]:
        """(count, tile_shape, dtype) per scratch pool in cid order —
        the single authority for scratch layout (build_pools and
        synth_pools both consume it)."""
        specs = []
        for sp in sorted(self._scratch.values(), key=lambda s: s["cid"]):
            if sp["shape"] is not None:
                specs.append((sp["n"], tuple(sp["shape"]),
                              np.dtype(sp["dtype"])))
            else:
                like = pools[sp["like"]]
                specs.append((sp["n"], tuple(like.shape[1:]),
                              np.dtype(str(like.dtype))))
        return specs

    def synth_pools(self, tile_fn=None, device=None,
                    pool_fn=None) -> Tuple:
        """Build pools entirely ON DEVICE inside one jit — zero H2D
        staging (benches/demos feed PRNG-generated inputs over a tunnel
        whose bandwidth cannot be trusted). Two synthesis granularities:

        - ``tile_fn(coll_name, coord) -> array``: simple, but the
          traced program is O(n_tiles) — a 4096-tile stack at NT=64
          produced a 360 KB MLIR module that OOM-killed the relay's
          compile helper;
        - ``pool_fn(coll_name, coords) -> stacked [len(coords), ...]``:
          the whole pool in one expression (vmap/scan inside keeps the
          program O(1) in tile count) — required at north-star sizes.

        Pool/scratch layout is identical to :meth:`build_pools` by
        construction (same pool walk, same :meth:`_scratch_specs`).
        The jitted builder is cached per function object — pass the
        SAME callable across calls to avoid a retrace per staging."""
        import jax
        import jax.numpy as jnp

        assert (tile_fn is None) != (pool_fn is None), \
            "pass exactly one of tile_fn / pool_fn"
        jitted = getattr(self, "_synth_jits", None)
        if jitted is None:
            jitted = self._synth_jits = {}
        cache_key = ("tile", tile_fn) if tile_fn is not None \
            else ("pool", pool_fn)
        fn = jitted.get(cache_key)
        if fn is None:
            def build():
                pools = []
                for pid, name in enumerate(self.pool_names):
                    if pid not in self._used_colls:
                        pools.append(jnp.zeros((0,), np.float32))
                        continue
                    coords = self._pool_coords[pid]
                    if pool_fn is not None:
                        pools.append(pool_fn(name, coords))
                    else:
                        pools.append(jnp.stack(
                            [tile_fn(name, c) for c in coords]))
                for cnt, shape, dt in self._scratch_specs(pools):
                    pools.append(jnp.zeros((cnt,) + shape, dt))
                return tuple(pools)
            fn = jitted[cache_key] = jax.jit(build)

        if device is not None:
            with jax.default_device(device):
                return fn()
        return fn()

    @staticmethod
    def _put_replicated(x, sharding):
        """Replicate an array over the sharding's mesh (scratch pools
        and pools whose tile shape the spec cannot divide)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = getattr(sharding, "mesh", None)
        if mesh is None:
            return jax.device_put(x, sharding)
        return jax.device_put(x, NamedSharding(mesh, PartitionSpec()))

    def _put_sharded(self, x, sharding):
        """Place one stacked pool under the caller's sharding spec;
        shape-split edge pools whose tile dims the spec does not divide
        fall back to mesh replication (small pools — the interior pool
        is the one that carries the FLOPs). Only the divisibility probe
        falls back: genuine spec/mesh errors from device_put propagate."""
        import jax
        try:
            sharding.shard_shape(tuple(x.shape))
        except ValueError as e:
            if "divid" not in str(e) and "evenly" not in str(e):
                raise   # malformed spec/mesh: the user must hear it
            plog.debug.verbose(
                2, "wave pool of tile shape %s not divisible by the "
                "sharding spec; replicating it on the mesh",
                tuple(x.shape[1:]))
            return self._put_replicated(x, sharding)
        return jax.device_put(x, sharding)

    def scatter_pools(self, pools: Tuple) -> None:
        for pid, name in enumerate(self.pool_names):
            if pid not in self._written_colls:
                continue  # no task wrote this pool: home copies stand
            coll = self.collections[name]
            host = np.asarray(pools[pid])
            for i, c in enumerate(self._pool_coords[pid]):
                data = coll.data_of(*c)
                hc = data.host_copy()
                if hc.payload is None:
                    hc.payload = host[i].copy()
                else:
                    np.copyto(hc.payload, host[i])
                data.version_bump(0)

    def run(self, device=None) -> None:
        pools = self.execute(self.build_pools(device))
        self.scatter_pools(pools)

    @property
    def nb_tasks(self) -> int:
        return self.dag.n_tasks


def wave(tp: PTGTaskpool, max_chunk: int = 256, comm=None) -> WaveRunner:
    """Build a wave-mode executor. Single-rank taskpools get the local
    WaveRunner; multi-rank taskpools (or an explicit ``comm``) get the
    distributed runner (wave_dist.py), which partitions the DAG by the
    data distribution and exchanges tiles between waves."""
    if tp.nb_ranks != 1 or comm is not None:
        from .wave_dist import DistWaveRunner
        return DistWaveRunner(tp, max_chunk=max_chunk, comm=comm)
    return WaveRunner(tp, max_chunk=max_chunk)
