"""Distributed tile GEMM (C = alpha A B + beta C) as a PTG task graph.

The SUMMA pattern as DPLASMA expresses it on the reference runtime:
owner-placed READ_A/READ_B tasks load each A/B tile at its home rank and
broadcast it over task edges to the full row/column of GEMM consumers
(the runtime fans the one output copy out via its bcast topologies,
parsec/remote_dep.c:272-358); each GEMM(m,n,k) accumulates C(m,n) in
place at C's home rank, chained over k. Tile body is one MXU matmul.

Transpose variants (transa/transb in {"n","t"}): the reader tasks index
the source collection as (m,k) or (k,m) — collection argument
expressions are Python, so the swap is a conditional on the TRANSA/
TRANSB globals — and the GEMM body transposes the tile operand before
the matmul (XLA folds the transpose into the dot's dimension numbers).
"""
from __future__ import annotations

from ..collections.matrix import TiledMatrix
from ..dsl import ptg

PDGEMM_JDF = """
descA [ type="collection" ]
descB [ type="collection" ]
descC [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]
KT [ type="int" ]
ALPHA [ type="float" default="1.0" ]
BETA [ type="float" default="1.0" ]
TRANSA [ type="string" default="'n'" ]
TRANSB [ type="string" default="'n'" ]

READ_A(m, k)

m = 0 .. MT-1
k = 0 .. KT-1

: descA( m if TRANSA == 'n' else k, k if TRANSA == 'n' else m )

READ A <- descA( m if TRANSA == 'n' else k, k if TRANSA == 'n' else m )
       -> A GEMM( m, 0 .. NT-1, k )

; (KT - k) * 10

BODY
{
    pass
}
END

READ_B(k, n)

k = 0 .. KT-1
n = 0 .. NT-1

: descB( k if TRANSB == 'n' else n, n if TRANSB == 'n' else k )

READ B <- descB( k if TRANSB == 'n' else n, n if TRANSB == 'n' else k )
       -> B GEMM( 0 .. MT-1, n, k )

; (KT - k) * 10

BODY
{
    pass
}
END

GEMM(m, n, k)

m = 0 .. MT-1
n = 0 .. NT-1
k = 0 .. KT-1

: descC( m, n )

READ A <- A READ_A( m, k )
READ B <- B READ_B( k, n )
RW   C <- (k == 0) ? descC( m, n ) : C GEMM( m, n, k-1 )
       -> (k == KT-1) ? descC( m, n ) : C GEMM( m, n, k+1 )

; KT - k

BODY [type=tpu]
{
    Ae = A if TRANSA == 'n' else jnp.swapaxes(A, 0, 1)
    Be = B if TRANSB == 'n' else jnp.swapaxes(B, 0, 1)
    C = ops.gemm(C, Ae, Be, float(ALPHA), float(BETA) if k == 0 else 1.0)
}
END
"""

_factory = None


def pdgemm_factory() -> "ptg.JDFFactory":
    global _factory
    if _factory is None:
        _factory = ptg.compile_jdf(PDGEMM_JDF, name="pdgemm")
    return _factory


def _eff(coll, trans):
    """(rows, cols) tile-grid / extents / tile dims after the transpose."""
    if trans == "n":
        return (coll.mt, coll.nt, coll.lm, coll.ln, coll.mb, coll.nb)
    return (coll.nt, coll.mt, coll.ln, coll.lm, coll.nb, coll.mb)


def pdgemm_taskpool(A: TiledMatrix, B: TiledMatrix, C: TiledMatrix,
                    alpha: float = 1.0, beta: float = 1.0,
                    transa: str = "n", transb: str = "n",
                    rank: int = 0, nb_ranks: int = 1):
    from .. import ops as ops_module
    if transa not in ("n", "t") or transb not in ("n", "t"):
        raise ValueError(f"pdgemm: transa/transb must be 'n' or 't', got "
                         f"{transa!r}/{transb!r}")
    amt, ant, alm, aln, amb, anb = _eff(A, transa)
    bmt, bnt, blm, bln, bmb, bnb = _eff(B, transb)
    if ant != bmt or amt != C.mt or bnt != C.nt:
        raise ValueError("pdgemm: inner/outer tile grids do not agree "
                         f"(opA {amt}x{ant}, opB {bmt}x{bnt}, "
                         f"C {C.mt}x{C.nt})")
    if aln != blm or alm != C.lm or bln != C.ln:
        raise ValueError("pdgemm: element extents do not agree "
                         f"(opA {alm}x{aln}, opB {blm}x{bln}, "
                         f"C {C.lm}x{C.ln})")
    if anb != bmb or amb != C.mb or bnb != C.nb:
        raise ValueError("pdgemm: tile sizes do not conform "
                         f"(opA {amb}x{anb}, opB {bmb}x{bnb}, "
                         f"C {C.mb}x{C.nb})")
    tp = pdgemm_factory().new(descA=A, descB=B, descC=C,
                              MT=C.mt, NT=C.nt, KT=ant,
                              ALPHA=float(alpha), BETA=float(beta),
                              TRANSA=transa, TRANSB=transb,
                              rank=rank, nb_ranks=nb_ranks)
    tp.global_env["ops"] = ops_module
    return tp


def pdgemm(context, A: TiledMatrix, B: TiledMatrix, C: TiledMatrix,
           alpha: float = 1.0, beta: float = 1.0,
           transa: str = "n", transb: str = "n",
           rank: int = 0, nb_ranks: int = 1) -> None:
    """C <- alpha op(A) op(B) + beta C over tiled collections. Blocking."""
    tp = pdgemm_taskpool(A, B, C, alpha=alpha, beta=beta,
                         transa=transa, transb=transb,
                         rank=rank, nb_ranks=nb_ranks)
    context.add_taskpool(tp)
    context.wait()
